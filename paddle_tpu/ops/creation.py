"""Creation ops (paddle.zeros/ones/arange/rand/... parity).

Reference parity: `python/paddle/tensor/creation.py` + `random.py`
[UNVERIFIED — empty reference mount].  All impls are pure jnp; random ops
thread the global Generator key (see framework/random.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.dtypes import convert_dtype, default_dtype
from ..core.tensor import Tensor, to_tensor
from ..framework.random import default_generator

__all__ = [
    "log_normal", "log_normal_",
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "rand", "randn", "randint", "randint_like", "uniform", "normal",
    "standard_normal", "randperm", "bernoulli", "multinomial", "poisson",
    "tril", "triu", "diag", "diagflat", "diag_embed", "meshgrid", "assign",
    "clone", "complex", "as_tensor", "uniform_", "normal_", "exponential_",
    "tril_indices", "triu_indices",
]


from ._helpers import _jd, _shape  # noqa: F401


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = "bool"
        elif isinstance(fill_value, int):
            dtype = "int64"
        else:
            dtype = default_dtype()
    return dispatch(
        "full", lambda *, shape, value, dtype: jnp.full(shape, value, dtype),
        (), dict(shape=_shape(shape), value=fill_value, dtype=_jd(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype, name)


from ._generated import zeros_like, ones_like  # noqa: F401
from ._generated import (  # noqa: F401  (sig-kind rows)
    clone,
    complex,
    diagflat,
    eye,
    full_like,
    linspace,
    logspace,
    ones,
    tril,
    triu,
    zeros,
)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        pass
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = "int64"
        else:
            dtype = default_dtype()
    return dispatch(
        "arange",
        lambda *, start, end, step, dtype: jnp.arange(start, end, step, dtype),
        (), dict(start=start, end=end, step=step, dtype=_jd(dtype)))


# ---------------- random ----------------

def _rng_dispatch(name, sampler, attrs):
    """Sample with the global generator key as a traced input; advance state."""
    g = default_generator()

    def impl(key, **at):
        new, sub = jax.random.split(key)
        return sampler(sub, **at), new

    out, newk = dispatch(name, impl, (g.state_tensor,), attrs,
                         differentiable=False)
    if isinstance(newk, Tensor):
        g.state_tensor._inplace_update(newk._value)
    return out


def rand(shape, dtype=None, name=None):
    return _rng_dispatch(
        "uniform_random",
        lambda k, *, shape, dtype: jax.random.uniform(k, shape, dtype),
        dict(shape=_shape(shape), dtype=_jd(dtype)))


def randn(shape, dtype=None, name=None):
    return _rng_dispatch(
        "gaussian_random",
        lambda k, *, shape, dtype: jax.random.normal(k, shape, dtype),
        dict(shape=_shape(shape), dtype=_jd(dtype)))


standard_normal = randn


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    if seed != 0:
        # paddle semantics: a non-zero seed fixes the sample (every
        # call returns the same values) without touching the global
        # generator state
        return dispatch(
            "uniform",
            lambda *, shape, dtype, lo, hi, seed: jax.random.uniform(
                jax.random.PRNGKey(seed), shape, dtype, lo, hi),
            (), dict(shape=_shape(shape), dtype=_jd(dtype),
                     lo=float(min), hi=float(max), seed=int(seed)),
            differentiable=False)
    return _rng_dispatch(
        "uniform",
        lambda k, *, shape, dtype, lo, hi: jax.random.uniform(
            k, shape, dtype, lo, hi),
        dict(shape=_shape(shape), dtype=_jd(dtype), lo=float(min),
             hi=float(max)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean if isinstance(mean, Tensor) else to_tensor(float(mean))
        s = std if isinstance(std, Tensor) else to_tensor(float(std))
        shp = tuple(np.broadcast_shapes(tuple(m.shape), tuple(s.shape)))
        z = randn(shp, dtype=m.dtype if m.dtype.is_floating_point() else None)
        from . import math as _math
        return _math.add(_math.multiply(z, s), m)
    return _rng_dispatch(
        "gaussian",
        lambda k, *, shape, dtype, mean, std: mean + std * jax.random.normal(
            k, shape, dtype),
        dict(shape=_shape(shape if shape is not None else []),
             dtype=_jd(None), mean=float(mean), std=float(std)))


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return _rng_dispatch(
        "randint",
        lambda k, *, shape, dtype, lo, hi: jax.random.randint(
            k, shape, lo, hi, dtype),
        dict(shape=_shape(shape), dtype=_jd(dtype, "int64"), lo=int(low),
             hi=int(high)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, tuple(x.shape),
                   dtype or x.dtype)


def randperm(n, dtype="int64", name=None):
    return _rng_dispatch(
        "randperm",
        lambda k, *, n, dtype: jax.random.permutation(k, n).astype(dtype),
        dict(n=int(n), dtype=_jd(dtype, "int64")))


def bernoulli(x, name=None):
    g = default_generator()

    def impl(key, p):
        new, sub = jax.random.split(key)
        return jax.random.bernoulli(sub, p).astype(p.dtype), new

    out, newk = dispatch("bernoulli", impl, (g.state_tensor, x), {},
                         differentiable=False)
    if isinstance(newk, Tensor):
        g.state_tensor._inplace_update(newk._value)
    return out


def multinomial(x, num_samples=1, replacement=False, name=None):
    g = default_generator()

    def impl(key, probs, *, n, repl):
        new, sub = jax.random.split(key)
        logits = jnp.log(jnp.maximum(probs, 1e-30))
        if repl:
            out = jax.random.categorical(sub, logits, axis=-1,
                                         shape=probs.shape[:-1] + (n,))
        else:
            z = jax.random.gumbel(sub, probs.shape, logits.dtype) + logits
            _, out = jax.lax.top_k(z, n)
        return out.astype(jnp.int64), new

    out, newk = dispatch("multinomial", impl, (g.state_tensor, x),
                         dict(n=int(num_samples), repl=bool(replacement)),
                         differentiable=False)
    if isinstance(newk, Tensor):
        g.state_tensor._inplace_update(newk._value)
    return out


def poisson(x, name=None):
    g = default_generator()

    def impl(key, lam):
        new, sub = jax.random.split(key)
        return jax.random.poisson(sub, lam).astype(lam.dtype), new

    out, newk = dispatch("poisson", impl, (g.state_tensor, x), {},
                         differentiable=False)
    if isinstance(newk, Tensor):
        g.state_tensor._inplace_update(newk._value)
    return out


def uniform_(x, min=-1.0, max=1.0, name=None):
    y = uniform(tuple(x.shape), x.dtype, min, max)
    x._inplace_update(y._value)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    y = normal(mean, std, tuple(x.shape))
    x._inplace_update(jnp.asarray(y._value, x._value.dtype))
    return x


def exponential_(x, lam=1.0, name=None):
    g = default_generator()
    key = g.next_key()
    x._inplace_update(
        jax.random.exponential(key, x._value.shape, x._value.dtype) / lam)
    return x


# ---------------- structured ----------------

def diag(x, offset=0, padding_value=0, name=None):
    def impl(v, *, k, pad):
        if v.ndim == 1:
            out = jnp.diag(v, k)
            if pad != 0:
                mask = jnp.eye(out.shape[0], out.shape[1], k, dtype=bool)
                out = jnp.where(mask, out, jnp.asarray(pad, out.dtype))
            return out
        return jnp.diagonal(v, k)

    return dispatch("diag", impl, (x,), dict(k=int(offset),
                                             pad=padding_value))


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def impl(v, *, k, d1, d2):
        n = v.shape[-1] + abs(k)
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-k, 0)
        c = idx + max(k, 0)
        out = out.at[..., r, c].set(v)
        # the two new axes materialize as the LAST two; move them to
        # the requested positions (paddle defaults dim1=-2, dim2=-1)
        nd = out.ndim
        d1, d2 = d1 % nd, d2 % nd
        if d1 == d2:
            raise ValueError(
                f"diag_embed: dim1 and dim2 must differ, both resolve "
                f"to {d1}")
        if (d1, d2) != (nd - 2, nd - 1):
            rest = [a for a in range(nd) if a not in (nd - 2, nd - 1)]
            perm = [None] * nd
            perm[d1], perm[d2] = nd - 2, nd - 1
            it = iter(rest)
            for i in range(nd):
                if perm[i] is None:
                    perm[i] = next(it)
            out = jnp.transpose(out, perm)
        return out

    return dispatch("diag_embed", impl, (x,),
                    dict(k=int(offset), d1=int(dim1), d2=int(dim2)))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = dispatch("meshgrid",
                    lambda *vs: tuple(jnp.meshgrid(*vs, indexing="ij")),
                    args, {})
    return list(outs)


def assign(x, output=None):
    if isinstance(x, Tensor):
        y = dispatch("assign", lambda v: v + 0 if False else jnp.asarray(v),
                     (x,), {})
    else:
        y = to_tensor(np.asarray(x))
    if output is not None:
        output._inplace_update(y._value, y._grad_node, y._out_index)
        return output
    return y


def as_tensor(data, dtype=None, place=None):
    return to_tensor(data, dtype=dtype, place=place)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    import numpy as _np
    from ..core.tensor import to_tensor
    col = row if col is None else col
    r, c = _np.tril_indices(int(row), k=int(offset), m=int(col))
    return to_tensor(_np.stack([r, c]).astype(_np.int64), dtype=dtype)


def triu_indices(row, col=None, offset=0, dtype="int64"):
    import numpy as _np
    from ..core.tensor import to_tensor
    col = row if col is None else col
    r, c = _np.triu_indices(int(row), k=int(offset), m=int(col))
    return to_tensor(_np.stack([r, c]).astype(_np.int64), dtype=dtype)


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    """Samples from LogNormal(mean, std) — exp of a normal draw
    (paddle.log_normal)."""
    from .math import exp as _exp
    return _exp(normal(float(mean), float(std),
                       shape if shape is not None else [1]))


def log_normal_(x, mean=1.0, std=2.0, name=None):
    v = log_normal(mean, std, list(x.shape))
    x._inplace_update(v._value.astype(x._value.dtype))
    return x
