"""Comparison & logical ops (paddle.tensor.logic parity).

Reference parity: `python/paddle/tensor/logic.py` [UNVERIFIED — empty
reference mount].
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose", "logical_and",
    "logical_or", "logical_not", "logical_xor", "bitwise_and", "bitwise_or",
    "bitwise_not", "bitwise_xor", "bitwise_left_shift", "bitwise_right_shift",
    "is_empty", "is_tensor", "in1d", "isin",
]


def _cmp(op_name, fn):
    # public `name=None` kwarg must not shadow the dispatch op name
    def op(x, y, name=None):
        return dispatch(op_name, fn, (x, y), {}, differentiable=False)
    op.__name__ = op_name
    return op


# comparison/logical bindings are GENERATED from ops.yaml
# (python -m paddle_tpu.ops.gen); bespoke-signature ops stay below
from ._generated import (  # noqa: F401
    equal, not_equal, greater_than, greater_equal, less_than, less_equal,
    logical_and, logical_or, logical_xor, bitwise_and, bitwise_or,
    bitwise_xor)
from ._generated import (  # noqa: F401  (sig-kind rows)
    allclose,
    bitwise_not,
    equal_all,
    isclose,
    isin,
    logical_not,
)

bitwise_left_shift = _cmp("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _cmp("bitwise_right_shift", jnp.right_shift)


def is_empty(x, name=None):
    return to_tensor(x.size == 0)


def is_tensor(x):
    return isinstance(x, Tensor)


in1d = isin
