"""Fused training-path Pallas kernels: layernorm+residual-add and
matmul-epilogue (bias + activation folded into the matmul consumer).

Reference parity: the reference ships these as hand-written CUDA
fusions — `fused_layernorm_residual_dropout_bias` and the cuBLASLt
epilogue path behind `fused_gemm_epilogue` [UNVERIFIED — empty
reference mount].

TPU-native design: same Mosaic tiling discipline as
`pallas_kernels.py` (this module reuses its helpers and the layer-norm
backward kernel outright — the LN+residual backward is the LN backward
with the saved sum `s = x + residual` in place of `x`, since
`d(x)/d(residual)` are identical).  Both kernels are `jax.custom_vjp`
so the eager tape and `to_static` differentiate through the
hand-written backward, and both export block plans
(`ln_residual_block_plan` / `matmul_epilogue_block_plan`) that
`analysis.tiling` verifies statically before anything touches the TPU.

Activation math is hand-differentiated in f32 inside the kernels; the
names mirror the XLA fallbacks the nn.functional layer keeps bit-exact:
``gelu`` = erf form (`jax.nn.gelu(approximate=False)`), ``gelu_tanh`` =
tanh form (`approximate=True`), ``silu``, ``relu``, ``none``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from .pallas_kernels import _ln_bwd_kernel
from .pallas_tiles import (_STAT_LANES, _demote_f64, _interpret,
                           _kernel_span, _ln_block_rows, _pad_dim,
                           _round_up, _x32, matmul_accum_blocks)

__all__ = [
    "ACTIVATIONS",
    "fused_layer_norm_residual",
    "fused_linear_act",
    "fused_linear_act_int8",
    "ln_residual_block_plan",
    "matmul_epilogue_block_plan",
]

ACTIVATIONS = ("none", "relu", "gelu", "gelu_tanh", "silu")

_SQRT_2 = 2.0 ** 0.5
_INV_SQRT_2PI = 0.3989422804014327     # 1/sqrt(2*pi)
_GELU_C = 0.7978845608028654           # sqrt(2/pi)
_GELU_A = 0.044715


def _act_f32(z, act):
    if act == "none":
        return z
    if act == "relu":
        return jnp.maximum(z, 0.0)
    if act == "gelu":
        return 0.5 * z * (1.0 + jax.lax.erf(z / _SQRT_2))
    if act == "gelu_tanh":
        t = jnp.tanh(_GELU_C * (z + _GELU_A * z * z * z))
        return 0.5 * z * (1.0 + t)
    if act == "silu":
        return z * jax.nn.sigmoid(z)
    raise ValueError(f"act must be one of {ACTIVATIONS}, got {act!r}")


def _act_grad_f32(z, act):
    if act == "none":
        return jnp.ones_like(z)
    if act == "relu":
        return (z > 0.0).astype(z.dtype)
    if act == "gelu":
        # d/dz [z*Phi(z)] = Phi(z) + z*phi(z)
        phi = _INV_SQRT_2PI * jnp.exp(-0.5 * z * z)
        return 0.5 * (1.0 + jax.lax.erf(z / _SQRT_2)) + z * phi
    if act == "gelu_tanh":
        u = _GELU_C * (z + _GELU_A * z * z * z)
        t = jnp.tanh(u)
        du = _GELU_C * (1.0 + 3.0 * _GELU_A * z * z)
        return 0.5 * (1.0 + t) + 0.5 * z * (1.0 - t * t) * du
    if act == "silu":
        s = jax.nn.sigmoid(z)
        return s * (1.0 + z * (1.0 - s))
    raise ValueError(f"act must be one of {ACTIVATIONS}, got {act!r}")


# =====================================================================
# Fused layernorm + residual add
# =====================================================================

def _ln_res_block_rows(rows, n):
    # the forward streams 4 (br, N) blocks (x, r, out, s) where plain LN
    # streams 2; halve the row budget so the double-buffered VMEM
    # estimate stays well under the 16MB ceiling at BERT-base widths
    return min(_ln_block_rows(rows, n), 256)


def _ln_res_fwd_kernel(x_ref, r_ref, g_ref, b_ref, o_ref, s_ref,
                       mu_ref, rstd_ref, *, eps):
    # add and statistics both run in f32; the saved sum is stored in
    # the input dtype (the residual stream's own precision)
    s = (x_ref[:].astype(jnp.float32)
         + r_ref[:].astype(jnp.float32))                # (block_rows, N)
    br = s.shape[0]
    mu = jnp.mean(s, axis=-1, keepdims=True)
    sc = s - mu
    var = jnp.mean(sc * sc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    shat = sc * rstd
    o_ref[:] = (shat * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    s_ref[:] = s.astype(s_ref.dtype)
    mu_ref[:] = jnp.broadcast_to(mu, (br, _STAT_LANES))
    rstd_ref[:] = jnp.broadcast_to(rstd, (br, _STAT_LANES))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_ln_residual_2d(x, r, gamma, beta, eps):
    return _fused_ln_residual_2d_fwd(x, r, gamma, beta, eps)[0]


@_x32
def _fused_ln_residual_2d_fwd(x, r, gamma, beta, eps):
    rows, n = x.shape
    br = _ln_res_block_rows(rows, n)
    rows_pad = _round_up(rows, br)
    xp = _pad_dim(x, 0, rows_pad)
    rp = _pad_dim(r, 0, rows_pad)
    with _kernel_span("layer_norm_residual", "fwd"):
        out, s, mu, rstd = pl.pallas_call(
            functools.partial(_ln_res_fwd_kernel, eps=eps),
            grid=(rows_pad // br,),
            in_specs=[
                pl.BlockSpec((br, n), lambda i: (i, 0)),
                pl.BlockSpec((br, n), lambda i: (i, 0)),
                pl.BlockSpec((1, n), lambda i: (0, 0)),
                pl.BlockSpec((1, n), lambda i: (0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((br, n), lambda i: (i, 0)),
                pl.BlockSpec((br, n), lambda i: (i, 0)),
                pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
                pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((rows_pad, n), x.dtype),
                jax.ShapeDtypeStruct((rows_pad, n), x.dtype),
                jax.ShapeDtypeStruct((rows_pad, _STAT_LANES), jnp.float32),
                jax.ShapeDtypeStruct((rows_pad, _STAT_LANES), jnp.float32),
            ],
            interpret=_interpret(),
        )(xp, rp, gamma.reshape(1, n), beta.reshape(1, n))
    return out[:rows], (s[:rows], gamma, mu, rstd)


@_x32
def _fused_ln_residual_2d_bwd(eps, res, do):
    s, gamma, mu, rstd = res
    rows, n = s.shape
    br = _ln_res_block_rows(rows, n)
    rows_pad = _round_up(rows, br)
    sp = _pad_dim(s, 0, rows_pad)
    dop = _pad_dim(do, 0, rows_pad)
    with _kernel_span("layer_norm_residual", "bwd"):
        dx, dg_acc, db_acc = pl.pallas_call(
            _ln_bwd_kernel,
            grid=(rows_pad // br,),
            in_specs=[
                pl.BlockSpec((br, n), lambda i: (i, 0)),
                pl.BlockSpec((1, n), lambda i: (0, 0)),
                pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
                pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
                pl.BlockSpec((br, n), lambda i: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((br, n), lambda i: (i, 0)),
                pl.BlockSpec((8, n), lambda i: (0, 0)),
                pl.BlockSpec((8, n), lambda i: (0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((rows_pad, n), s.dtype),
                jax.ShapeDtypeStruct((8, n), jnp.float32),
                jax.ShapeDtypeStruct((8, n), jnp.float32),
            ],
            interpret=_interpret(),
        )(sp, gamma.reshape(1, n), mu, rstd, dop)
    dgamma = dg_acc[0].astype(gamma.dtype)
    dbeta = db_acc[0].astype(gamma.dtype)
    dx = dx[:rows]
    return dx, dx, dgamma, dbeta  # d(x) == d(residual)


_fused_ln_residual_2d.defvjp(_fused_ln_residual_2d_fwd,
                             _fused_ln_residual_2d_bwd)


def fused_layer_norm_residual(x, residual, gamma, beta, eps=1e-5):
    """LayerNorm(x + residual) over the last dim, fused; differentiable.

    The residual add, mean/variance, normalize and affine all run in a
    single VMEM pass (one read of x/residual instead of the unfused
    add-then-norm's two), and the backward reuses the plain LN backward
    on the saved sum.
    """
    x, residual, gamma, beta = _demote_f64(x, residual, gamma, beta)
    shape = x.shape
    n = shape[-1]
    out = _fused_ln_residual_2d(x.reshape(-1, n), residual.reshape(-1, n),
                                gamma, beta, float(eps))
    return out.reshape(shape)


def ln_residual_block_plan(rows, hidden, dtype=jnp.float32,
                           direction="fwd"):
    """The exact block plan the LN+residual kernels use for (rows, N).

    Same contract as `flash_block_plan`: per-operand (name, block_shape,
    padded_array_shape, dtype) in pallas_call order, statically
    checkable by `analysis.tiling.check_pallas_call`.  Keep in lockstep
    with `_fused_ln_residual_2d_fwd` / `_fused_ln_residual_2d_bwd`.
    """
    dtype = jnp.dtype(dtype)
    f32 = jnp.dtype(jnp.float32)
    n = hidden
    br = _ln_res_block_rows(rows, n)
    rows_pad = _round_up(rows, br)
    row_blk = lambda name, dt: (  # noqa: E731 - local table helper
        name, (br, n), (rows_pad, n), dt)
    stat = lambda name: (  # noqa: E731
        name, (br, _STAT_LANES), (rows_pad, _STAT_LANES), f32)
    if direction == "fwd":
        operands = [
            row_blk("x", dtype), row_blk("residual", dtype),
            ("gamma", (1, n), (1, n), dtype),
            ("beta", (1, n), (1, n), dtype),
            row_blk("out", dtype), row_blk("s", dtype),
            stat("mu"), stat("rstd"),
        ]
    elif direction == "bwd":
        operands = [
            row_blk("s", dtype),
            ("gamma", (1, n), (1, n), dtype),
            stat("mu"), stat("rstd"),
            row_blk("do", dtype), row_blk("dx", dtype),
            ("dgamma", (8, n), (8, n), f32),
            ("dbeta", (8, n), (8, n), f32),
        ]
    else:
        raise ValueError(f"direction must be fwd|bwd, got {direction!r}")
    return {
        "direction": direction,
        "grid": (rows_pad // br,),
        "block_rows": br,
        "operands": operands,
        "scratch": (),
    }


# =====================================================================
# Matmul-epilogue fusion: act(x @ w + b)
# =====================================================================

def _me_fwd_kernel(x_ref, w_ref, b_ref, o_ref, z_ref, *, act):
    # f32 operands: Mosaic's tpu.matmul rejects bf16 inputs here (same
    # convention as the flash kernels); accumulation + epilogue in f32
    z = jax.lax.dot_general(
        x_ref[:].astype(jnp.float32), w_ref[:].astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # (bm, bn)
    z = z + b_ref[:].astype(jnp.float32)
    z_ref[:] = z.astype(z_ref.dtype)
    o_ref[:] = _act_f32(z, act).astype(o_ref.dtype)


def _me_bwd_kernel(z_ref, g_ref, dz_ref, db_ref, *, act):
    z = z_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    dz = g * _act_grad_f32(z, act)
    dz_ref[:] = dz.astype(dz_ref.dtype)

    # dbias: sequential-grid accumulation — the grid is (n_blocks,
    # m_blocks) with m minor, so every revisit of this db block is
    # consecutive
    @pl.when(pl.program_id(1) == 0)
    def _init():
        db_ref[:] = jnp.zeros_like(db_ref)

    db = jnp.sum(dz, axis=0, keepdims=True)             # (1, bn)
    db_ref[:] = db_ref[:] + jnp.broadcast_to(db, db_ref.shape)


def _me_blocks(m, k, n, dtype):
    """(bm, bn, m_pad, n_pad): the shared k-blocked f32 accumulator
    plan (`pallas_tiles.matmul_accum_blocks`) at this dtype."""
    return matmul_accum_blocks(m, k, n, dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _matmul_epilogue_2d(x, w, b, act):
    return _matmul_epilogue_2d_fwd(x, w, b, act)[0]


@_x32
def _matmul_epilogue_2d_fwd(x, w, b, act):
    m, k = x.shape
    n = w.shape[1]
    bm, bn, m_pad, n_pad = _me_blocks(m, k, n, x.dtype)
    xp = _pad_dim(x, 0, m_pad)
    wp = _pad_dim(w, 1, n_pad)
    bp = _pad_dim(b.reshape(1, n), 1, n_pad)
    with _kernel_span("matmul_epilogue", "fwd"):
        out, z = pl.pallas_call(
            functools.partial(_me_fwd_kernel, act=act),
            grid=(m_pad // bm, n_pad // bn),
            in_specs=[
                pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
                pl.BlockSpec((k, bn), lambda i, j: (0, j)),
                pl.BlockSpec((1, bn), lambda i, j: (0, j)),
            ],
            out_specs=[
                pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
                pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((m_pad, n_pad), x.dtype),
                jax.ShapeDtypeStruct((m_pad, n_pad), x.dtype),
            ],
            interpret=_interpret(),
        )(xp, wp, bp)
    return out[:m, :n], (x, w, b, z[:m, :n])


@_x32
def _matmul_epilogue_2d_bwd(act, res, g):
    x, w, b, z = res
    m, k = x.shape
    n = w.shape[1]
    bm, bn, m_pad, n_pad = _me_blocks(m, k, n, x.dtype)
    zp = _pad_dim(_pad_dim(z, 0, m_pad), 1, n_pad)
    gp = _pad_dim(_pad_dim(g, 0, m_pad), 1, n_pad)
    with _kernel_span("matmul_epilogue", "bwd"):
        dz_pad, db_acc = pl.pallas_call(
            functools.partial(_me_bwd_kernel, act=act),
            grid=(n_pad // bn, m_pad // bm),
            in_specs=[
                pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
                pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
            ],
            out_specs=[
                pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
                pl.BlockSpec((8, bn), lambda j, i: (0, j)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((m_pad, n_pad), x.dtype),
                jax.ShapeDtypeStruct((8, n_pad), jnp.float32),
            ],
            interpret=_interpret(),
        )(zp, gp)
    dz = dz_pad[:m, :n]
    # dx / dw are plain matmuls XLA already schedules optimally — the
    # fusion win is the epilogue, so hand these back to XLA
    dx = jax.lax.dot_general(
        dz, w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    dw = jax.lax.dot_general(
        x, dz, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(w.dtype)
    db = db_acc[0, :n].astype(b.dtype)
    return dx, dw, db


_matmul_epilogue_2d.defvjp(_matmul_epilogue_2d_fwd,
                           _matmul_epilogue_2d_bwd)


def fused_linear_act(x, w, b, act="none"):
    """act(x @ w + b) with bias + activation fused into the matmul
    consumer; differentiable.  x: [..., K]; w: [K, N]; b: [N]."""
    if act not in ACTIVATIONS:
        raise ValueError(f"act must be one of {ACTIVATIONS}, got {act!r}")
    x, w, b = _demote_f64(x, w, b)
    shape = x.shape
    k = shape[-1]
    n = w.shape[-1]
    out = _matmul_epilogue_2d(x.reshape(-1, k), w, b.reshape(n), act)
    return out.reshape(shape[:-1] + (n,))


# =====================================================================
# Int8-weight matmul epilogue: act((x @ w_int8) * scale + b)
# =====================================================================
#
# The weight lives in HBM as int8 with one f32 scale per OUTPUT channel.
# Per-output-channel dequant commutes with the contraction —
# x @ (w_q * diag(s)) == (x @ w_q) * s — so the kernel keeps the int8
# tiles all the way into VMEM (half the weight bandwidth of bf16, a
# quarter of f32) and applies the scale once on the f32 accumulator:
# one multiply per OUTPUT element instead of one per weight element.
# The XLA fallback in nn.functional must use the same post-dot op order
# to stay bit-exact with the interpret-mode kernel.


def _me_int8_fwd_kernel(x_ref, w_ref, s_ref, b_ref, o_ref, z_ref, *, act):
    # tpu.matmul wants f32 operands (same convention as _me_fwd_kernel);
    # the int8 -> f32 widening happens on the VMEM-resident tile, AFTER
    # the (k, bn) block travelled HBM->VMEM at 1 byte/element
    z = jax.lax.dot_general(
        x_ref[:].astype(jnp.float32), w_ref[:].astype(jnp.float32),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # (bm, bn)
    z = z * s_ref[:] + b_ref[:].astype(jnp.float32)     # dequant epilogue
    z_ref[:] = z.astype(z_ref.dtype)
    o_ref[:] = _act_f32(z, act).astype(o_ref.dtype)


def _me_int8_blocks(m, k, n, x_dtype):
    """(bm, bn, m_pad, n_pad) for the int8-weight variant: the VMEM
    ceiling is driven by the double-buffered (K, bn) weight block at
    1 byte/element, so bn can run wider than the float kernel's; bm
    still follows the ACTIVATION dtype (x is not int8)."""
    return matmul_accum_blocks(m, k, n, x_dtype, weight_itemsize=1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _matmul_epilogue_int8_2d(x, w_q, scale, b, act):
    return _matmul_epilogue_int8_2d_fwd(x, w_q, scale, b, act)[0]


@_x32
def _matmul_epilogue_int8_2d_fwd(x, w_q, scale, b, act):
    m, k = x.shape
    n = w_q.shape[1]
    bm, bn, m_pad, n_pad = _me_int8_blocks(m, k, n, x.dtype)
    xp = _pad_dim(x, 0, m_pad)
    wp = _pad_dim(w_q, 1, n_pad)
    # padded channels get scale 1.0 so the bwd dscale division below
    # never sees a synthetic zero (their columns are sliced off anyway)
    sp = _pad_dim(scale.reshape(1, n).astype(jnp.float32), 1, n_pad, 1.0)
    bp = _pad_dim(b.reshape(1, n), 1, n_pad)
    with _kernel_span("matmul_epilogue_int8", "fwd"):
        out, z = pl.pallas_call(
            functools.partial(_me_int8_fwd_kernel, act=act),
            grid=(m_pad // bm, n_pad // bn),
            in_specs=[
                pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
                pl.BlockSpec((k, bn), lambda i, j: (0, j)),
                pl.BlockSpec((1, bn), lambda i, j: (0, j)),
                pl.BlockSpec((1, bn), lambda i, j: (0, j)),
            ],
            out_specs=[
                pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
                pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((m_pad, n_pad), x.dtype),
                jax.ShapeDtypeStruct((m_pad, n_pad), x.dtype),
            ],
            interpret=_interpret(),
        )(xp, wp, sp, bp)
    return out[:m, :n], (x, w_q, scale, b, z[:m, :n])


@_x32
def _matmul_epilogue_int8_2d_bwd(act, res, g):
    x, w_q, scale, b, z = res
    m, k = x.shape
    n = w_q.shape[1]
    bm, bn, m_pad, n_pad = _me_int8_blocks(m, k, n, x.dtype)
    zp = _pad_dim(_pad_dim(z, 0, m_pad), 1, n_pad)
    gp = _pad_dim(_pad_dim(g, 0, m_pad), 1, n_pad)
    # dz/db epilogue backward is dtype-agnostic over z/g — reuse the
    # float kernel at the int8 plan's block sizes
    with _kernel_span("matmul_epilogue_int8", "bwd"):
        dz_pad, db_acc = pl.pallas_call(
            functools.partial(_me_bwd_kernel, act=act),
            grid=(n_pad // bn, m_pad // bm),
            in_specs=[
                pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
                pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
            ],
            out_specs=[
                pl.BlockSpec((bm, bn), lambda j, i: (i, j)),
                pl.BlockSpec((8, bn), lambda j, i: (0, j)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((m_pad, n_pad), x.dtype),
                jax.ShapeDtypeStruct((8, n_pad), jnp.float32),
            ],
            interpret=_interpret(),
        )(zp, gp)
    dz = dz_pad[:m, :n]
    s32 = scale.reshape(n).astype(jnp.float32)
    # the weight is dequantized ONCE for dx; the quantized tensor
    # itself is integer (no cotangent), but the per-channel scale is a
    # live float leaf — its grad falls out of the saved pre-activation:
    # z = (x @ w_q) * s + b  =>  dz/ds_j = (z_j - b_j) / s_j
    w_deq = w_q.astype(jnp.float32) * s32[None, :]
    dx = jax.lax.dot_general(
        dz, w_deq, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32).astype(x.dtype)
    dz32 = dz.astype(jnp.float32)
    acc = (z.astype(jnp.float32) - b.reshape(1, n).astype(jnp.float32))
    dscale = jnp.sum(dz32 * acc, axis=0) / s32
    db = db_acc[0, :n].astype(b.dtype)
    dw_q = np.zeros(w_q.shape, dtype=jax.dtypes.float0)
    return dx, dw_q, dscale.astype(scale.dtype), db


_matmul_epilogue_int8_2d.defvjp(_matmul_epilogue_int8_2d_fwd,
                                _matmul_epilogue_int8_2d_bwd)


def fused_linear_act_int8(x, w_q, scale, b, act="none"):
    """act((x @ w_int8) * scale + b) with the per-output-channel dequant
    fused into the matmul accumulator; differentiable in x, scale, b.

    x: [..., K] float; w_q: [K, N] int8; scale: [N] f32 per-channel
    dequant scales; b: [N].  The int8 weight is a frozen constant
    (integer primal, float0 cotangent).
    """
    if act not in ACTIVATIONS:
        raise ValueError(f"act must be one of {ACTIVATIONS}, got {act!r}")
    if jnp.dtype(w_q.dtype) != jnp.dtype(jnp.int8):
        raise ValueError(f"w_q must be int8, got {w_q.dtype}")
    x, b = _demote_f64(x, b)
    scale = jnp.asarray(scale, jnp.float32)
    shape = x.shape
    k = shape[-1]
    n = w_q.shape[-1]
    out = _matmul_epilogue_int8_2d(x.reshape(-1, k), w_q,
                                   scale.reshape(n), b.reshape(n), act)
    return out.reshape(shape[:-1] + (n,))


def matmul_epilogue_block_plan(m, k, n, dtype=jnp.float32,
                               direction="fwd", weight_dtype=None):
    """The exact block plan `_matmul_epilogue_2d_{fwd,bwd}` uses for
    an (m, k) @ (k, n) problem.  Same contract as `flash_block_plan`.

    ``weight_dtype=int8`` exports the `_matmul_epilogue_int8_2d` plan
    instead: int8 (k, bn) weight blocks + an f32 (1, bn) per-channel
    scale operand; the activation/output dtype stays ``dtype``.
    """
    dtype = jnp.dtype(dtype)
    f32 = jnp.dtype(jnp.float32)
    wdt = jnp.dtype(weight_dtype) if weight_dtype is not None else dtype
    int8_w = wdt == jnp.dtype(jnp.int8)
    if int8_w:
        bm, bn, m_pad, n_pad = _me_int8_blocks(m, k, n, dtype)
    else:
        bm, bn, m_pad, n_pad = _me_blocks(m, k, n, dtype)
    out_blk = lambda name: (  # noqa: E731 - local table helper
        name, (bm, bn), (m_pad, n_pad), dtype)
    if direction == "fwd":
        grid = (m_pad // bm, n_pad // bn)
        operands = [
            ("x", (bm, k), (m_pad, k), dtype),
            ("w", (k, bn), (k, n_pad), wdt),
        ]
        if int8_w:
            operands.append(("scale", (1, bn), (1, n_pad), f32))
        operands += [
            ("b", (1, bn), (1, n_pad), dtype),
            out_blk("out"), out_blk("z"),
        ]
    elif direction == "bwd":
        grid = (n_pad // bn, m_pad // bm)
        operands = [
            out_blk("z"), out_blk("g"), out_blk("dz"),
            ("db", (8, bn), (8, n_pad), f32),
        ]
    else:
        raise ValueError(f"direction must be fwd|bwd, got {direction!r}")
    return {
        "direction": direction,
        "grid": grid,
        "block_m": bm,
        "block_n": bn,
        "weight_dtype": str(wdt),
        "operands": operands,
        "scratch": (),
    }
