"""Shared tile primitives for the Pallas kernel suite.

ThunderKittens (arxiv 2410.20399) argues a small set of reusable
tile/layout primitives covers the fast-kernel design space; this module
is that layer for the ~9-kernel suite (flash, paged, ragged, fused
LN/RMS/xent, matmul-epilogue, grouped-expert).  Everything here is
shape/layout/tracing policy — no kernel bodies:

  * tracing + dispatch policy: `_x32` (trace pallas_call builders under
    x32 because the framework globally enables x64), `_interpret`
    (interpret mode off-TPU), `_kernel_span` (timeline attribution);
  * dtype-aware block picking: `_min_rows` (Mosaic sublane minima),
    `_sane_block` (clamp requested blocks to legality),
    `_ln_block_rows` / `_xent_blocks` (VMEM-budgeted row/vocab blocks),
    `matmul_accum_blocks` (full-K resident rows, N split under a VMEM
    weight-block budget — the k-blocked f32 accumulator plan shared by
    matmul-epilogue, its int8 variant, and the grouped-expert matmul);
  * running-softmax scratch: `softmax_scratch` / `stat_scratch` (the
    acc/m/l VMEM triplet every online-softmax kernel carries across a
    sequential grid dim);
  * segment descriptors: `group_segments` (block-aligned per-group
    descriptors driving scalar-prefetched BlockSpec index maps) and
    `num_group_blocks` (their static grid bound);
  * layout utilities: `_round_up`, `_pad_dim`, `_lanes` (stat-lane
    broadcast), `_demote_f64`, `_NEG_INF`, `_STAT_LANES`.

Every kernel module binds these by `from .pallas_tiles import ...`, so
a helper is ONE object process-wide — the bit-identity guarantee of the
refactor is that the kernels call the same code they inlined before.
Tooling that monkeypatches `_interpret` (scripts/aot_check_kernels.py)
must patch each kernel module's own global, as before.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# pltpu is importable on CPU builds of jax as well; the VMEM scratch
# helpers below require it even in interpret mode
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "group_segments",
    "matmul_accum_blocks",
    "num_group_blocks",
    "softmax_scratch",
    "stat_scratch",
]

_NEG_INF = -1e30
_STAT_LANES = 8  # trailing lane dim for per-row stat arrays

try:
    from jax._src.config import enable_x64 as _enable_x64_ctx
except ImportError:  # pragma: no cover - fallback for jax API moves
    import contextlib

    @contextlib.contextmanager
    def _enable_x64_ctx(value):
        old = jax.config.jax_enable_x64
        jax.config.update("jax_enable_x64", value)
        try:
            yield
        finally:
            jax.config.update("jax_enable_x64", old)


def _x32(fn):
    """Trace the wrapped pallas_call builder under x32 semantics.

    The framework enables jax_enable_x64 globally (paddle_tpu/__init__.py)
    for Paddle's int64/float64 tensor semantics.  Under x64, Pallas
    index-map literals and in-kernel weak ints trace as i64, which Mosaic
    cannot legalize ("failed to legalize func.return (i32, i64)") and
    whose int64 converts send Mosaic's _convert_helper into infinite
    recursion — this was the root cause of ALL four round-2 kernel
    failures on hardware.  Every dtype inside the kernels is explicit
    (f32/bf16/i32), so tracing them x32 changes nothing numerically.
    """
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with _enable_x64_ctx(False):
            return fn(*args, **kwargs)
    return wrapper


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _kernel_span(name: str, direction: str):
    """Timeline span around one pallas_call build+dispatch.

    Spans land in the ``kernel`` category so `phase_breakdown()` can
    attribute step time per kernel and direction
    (``kernel_<name>_<direction>_ms``).  The timeline returns a no-op
    singleton when observability is disabled, so this costs one global
    read on the hot path.
    """
    from ..observability.timeline import span
    return span(f"kernel:{name}.{direction}", cat="kernel")


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pad_dim(x, dim, target, value=0.0):
    pad = target - x.shape[dim]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[dim] = (0, pad)
    # dtype-matched fill: a python float is a strong f64 under the
    # framework's global x64 mode and would promote the padded array
    return jnp.pad(x, widths, constant_values=jnp.asarray(value, x.dtype))


def _lanes(x2d):
    """Broadcast a (rows,) or (rows, 1) stat to the stat-lane layout."""
    if x2d.ndim == 1:
        x2d = x2d[:, None]
    return jnp.broadcast_to(x2d, x2d.shape[:-1] + (_STAT_LANES,))


def _demote_f64(*xs):
    """TPU has no float64: demote f64 inputs to f32 (grad flows back
    through the cast).  The global x64 mode (paddle_tpu/__init__.py)
    makes f64 a reachable input dtype on the CPU test path."""
    return tuple(
        x.astype(jnp.float32) if x is not None
        and jnp.issubdtype(x.dtype, jnp.floating)
        and jnp.dtype(x.dtype).itemsize == 8 else x
        for x in xs)


# =====================================================================
# Dtype-aware block picking
# =====================================================================

def _min_rows(dtype) -> int:
    """Mosaic minimum sublane rows for `dtype`: 8 for 4-byte, 16 for
    2-byte (bf16/f16), 32 for 1-byte tiles."""
    return {1: 32, 2: 16}.get(jnp.dtype(dtype).itemsize, 8)


def _sane_block(b, seq, min_rows=16):
    """Clamp any requested block to a legal tiling for `seq`/`dtype`."""
    try:
        b = int(b)
    except (TypeError, ValueError):
        return None
    if b < min_rows or b % min_rows:
        return None
    return min(b, _round_up(max(seq, min_rows), min_rows))


def _ln_block_rows(rows, n, itemsize=4):
    # keep a block under ~2MB of f32 VMEM working set; 16-row multiples
    # keep bf16 blocks on whole (16, 128) tiles
    budget = max(1, (2 << 20) // max(n * itemsize, 1))
    return min(_round_up(rows, 16), max(16, min(512, _round_up(budget, 16))))


def _xent_blocks(rows, v):
    """(block_rows, block_v, rows_pad, v_pad) with bounded VMEM."""
    bv = min(_round_up(v, 128), 2048)
    br = min(_round_up(rows, 16), 256)
    return br, bv, _round_up(rows, br), _round_up(v, bv)


def matmul_accum_blocks(m, k, n, dtype, weight_itemsize=None):
    """(bm, bn, m_pad, n_pad) for a full-K f32-accumulator matmul:
    resident (bm, K) rows, N split so the double-buffered (K, bn)
    weight block stays under ~6MB of VMEM.

    ``weight_itemsize`` sizes the weight-block budget independently of
    the activation dtype (int8 weights travel at 1 byte/element so bn
    can run wider); default is the activation dtype's own itemsize.
    This is the shared accumulator plan of `matmul_epilogue`, its int8
    variant, and the grouped-expert matmul.
    """
    itemsize = weight_itemsize or jnp.dtype(dtype).itemsize
    bm = min(_round_up(max(m, 1), _min_rows(dtype)), 128)
    bn = 512
    while bn > 128 and 2 * k * bn * itemsize > (6 << 20):
        bn //= 2
    bn = min(bn, _round_up(max(n, 1), 128))
    return bm, bn, _round_up(m, bm), _round_up(n, bn)


# =====================================================================
# Running-softmax / accumulator scratch
# =====================================================================

def softmax_scratch(rows, width):
    """The acc/m/l VMEM triplet of an online-softmax accumulation:
    (rows, width) f32 weighted-value accumulator plus (rows,
    _STAT_LANES) running max and running sum-exp, persisting across a
    sequential innermost grid dim (paged/ragged attention pattern)."""
    return [
        pltpu.VMEM((rows, width), jnp.float32),
        pltpu.VMEM((rows, _STAT_LANES), jnp.float32),
        pltpu.VMEM((rows, _STAT_LANES), jnp.float32),
    ]


def stat_scratch(rows, count):
    """``count`` per-row f32 stat accumulators in the stat-lane layout
    (the xent kernel's running max / sum-exp / picked-logit pattern)."""
    return [pltpu.VMEM((rows, _STAT_LANES), jnp.float32)
            for _ in range(count)]


# =====================================================================
# Segment descriptors (block-aligned grouping)
# =====================================================================

def num_group_blocks(total_rows, num_groups, block_rows):
    """Static upper bound on the number of `block_rows`-row blocks
    needed to cover `total_rows` rows split into `num_groups`
    block-aligned groups: each group wastes less than one block of
    padding, so cdiv(total) + num_groups always suffices."""
    return -(-total_rows // block_rows) + num_groups


def group_segments(group_sizes, block_rows, num_blocks):
    """Block-aligned segment descriptors for grouped (per-expert) rows.

    ``group_sizes``: [G] int32 row counts (traced is fine).  Each
    group's rows are padded up to a `block_rows` multiple so every
    block is wholly owned by one group — the grouped-matmul analogue of
    `pallas_ragged.ragged_segments`'s per-q-block descriptors.

    Returns ``(block_group, group_row_offsets)``:
      * ``block_group``: [num_blocks] int32, the group owning each
        block; blocks past the padded total get the null id ``G``
        (callers append a zero row to the indexed operand, exactly like
        the ragged kernels' null segment);
      * ``group_row_offsets``: [G] int32, the first padded row of each
        group — dispatch scatters token ``j`` of group ``g`` to row
        ``group_row_offsets[g] + j``.
    """
    gs = jnp.asarray(group_sizes, jnp.int32)
    nblk = (gs + block_rows - 1) // block_rows            # [G]
    ends = jnp.cumsum(nblk)                               # [G]
    starts = ends - nblk
    i = jnp.arange(num_blocks, dtype=jnp.int32)
    # block i belongs to the group whose [starts, ends) contains it ==
    # the count of ends <= i; empty groups collapse to zero-width
    # intervals that can never claim a block, and blocks past ends[-1]
    # land on the null id G
    gid = jnp.searchsorted(ends, i, side="right").astype(jnp.int32)
    return gid, (starts * block_rows).astype(jnp.int32)
