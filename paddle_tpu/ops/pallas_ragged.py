"""Ragged paged attention: ONE kernel for mixed prefill+decode batches.

The serving engine (inference/serving/engine.py) packs every scheduled
token of a step — one prefill *chunk* plus every decode row — into a
single flat, block-aligned query buffer

    q: [T, H, D]      T = num_q_blocks * block_q

where each sequence owns a run of whole ``block_q``-row q-blocks
(*Ragged Paged Attention*, PAPERS.md / arxiv 2604.15464).  Three
per-q-block scalar arrays describe the ragged layout:

    seq_ids[i]   which sequence q-block ``i`` belongs to
                 (``num_seqs`` = null segment: all rows padding)
    q_starts[i]  absolute KV position of the block's first row,
                 i.e. ``context_len - query_len + i_local * block_q``
    q_valids[i]  valid rows in the block (trailing rows are padding)

K/V live in the PR-5 paged pool ``[num_blocks, H, block_size, D]``;
``block_tables [S, W]`` / ``context_lens [S]`` are scalar-prefetched
exactly like `paged_attention`, and the grid is

    (num_q_blocks, num_heads, W)     w innermost, sequential

so the online-softmax state (acc/m/l) in VMEM scratch survives the
walk over a sequence's KV blocks.  Causal masking happens inside each
ragged segment: row ``r`` of q-block ``i`` sees KV position ``c`` iff

    r < q_valids[i]  and  c <= q_starts[i] + r  and  c < context_len

which makes a decode row (query_len 1, start ``ctx-1``) and a prefill
chunk row fall out of the same predicate.  A fully masked row keeps
``l == 0`` and emits exact zeros — the same any-visible semantics as
the XLA fallback (`serving/attention._ragged_ref`) and the dense paged
kernel.

Gated through ``pallas_gate`` ("ragged_attention" probe);
`ragged_block_plan` exports the exact specs for
`analysis.tiling.audit_ragged_attention` / tpu_lint.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_tiles import (_NEG_INF, _STAT_LANES, _demote_f64,
                           _interpret, _kernel_span, _lanes, _min_rows,
                           _x32, softmax_scratch)

__all__ = ["ragged_paged_attention", "ragged_block_plan",
           "ragged_q_block", "ragged_segments", "KV_SCALE_LANES"]

#: lane width of the per-slot KV dequant scale tables
#: ``[num_blocks, block_size, KV_SCALE_LANES]`` (f32).  One lane keeps
#: the int8 pool's scale overhead at 4 bytes per slot-layer so the
#: capacity win stays ~2x even at small head_dim; both trailing dims of
#: the (1, block_size, 1) scale block cover the full array, which keeps
#: the spec legal at any lane count.
KV_SCALE_LANES = 1


def ragged_q_block(dtype) -> int:
    """Rows per ragged q-block: the Mosaic minimum sublane count for
    ``dtype`` (8 f32 / 16 bf16), never below the stat-lane width."""
    return max(_STAT_LANES, _min_rows(jnp.dtype(dtype)))


def ragged_segments(query_lens, context_lens, block_q,
                    num_q_blocks=None, num_seqs=None):
    """Host-side ragged layout for a mixed batch (numpy, no tracing).

    Returns ``(seq_ids, q_starts, q_valids, offsets, total_rows)``:
    per-q-block descriptor arrays (padded to ``num_q_blocks`` with the
    ``num_seqs`` null segment when given) plus each sequence's flat row
    offset and the total flat rows used.
    """
    query_lens = [int(x) for x in query_lens]
    context_lens = [int(x) for x in context_lens]
    if num_seqs is None:
        num_seqs = len(query_lens)
    sids, starts, valids, offsets = [], [], [], []
    off = 0
    for s, (ql, cl) in enumerate(zip(query_lens, context_lens)):
        offsets.append(off)
        if ql == 0:
            continue
        if ql > cl:
            raise ValueError(
                f"sequence {s}: query_len {ql} > context_len {cl}")
        base = cl - ql
        nseg = -(-ql // block_q)
        for j in range(nseg):
            sids.append(s)
            starts.append(base + j * block_q)
            valids.append(min(block_q, ql - j * block_q))
        off += nseg * block_q
    if num_q_blocks is not None:
        if len(sids) > num_q_blocks:
            raise ValueError(
                f"{len(sids)} q-blocks exceed budget {num_q_blocks}")
        pad = num_q_blocks - len(sids)
        sids += [num_seqs] * pad
        starts += [0] * pad
        valids += [0] * pad
    return (np.asarray(sids, np.int32), np.asarray(starts, np.int32),
            np.asarray(valids, np.int32),
            np.asarray(offsets, np.int32), off)


def _ragged_attn_body(bt_ref, cl_ref, sid_ref, qs_ref, qv_ref,
                      q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                      acc_ref, m_ref, l_ref, *, block_size, block_q,
                      scale, w_last):
    """One (q-block, head, table-slot) program over the paged pool.

    Scalar-prefetched ``seq_ids`` route each q-block to its sequence's
    block table; the null segment (``seq_ids == num_seqs``) reads
    ``context_len 0`` from the padded tail of ``cl_ref`` so its guard
    never fires and the emit writes zeros.

    ``ks_ref``/``vs_ref`` are the int8 variant's per-slot dequant scale
    blocks ((1, block_size, KV_SCALE_LANES) f32, walked by the SAME
    block-table index map as k/v) or None on the float path; dequant
    happens on the VMEM-resident tile inside the running-softmax loop —
    the int8 bytes are all that crosses HBM.
    """
    i = pl.program_id(0)
    w = pl.program_id(2)
    sid = sid_ref[i]
    ctx = cl_ref[sid]
    qs = qs_ref[i]
    qv = qv_ref[i]

    @pl.when(w == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(w * block_size < ctx)
    def _block():
        q = q_ref[0].astype(jnp.float32)                # (bq, D)
        k = k_ref[0, 0].astype(jnp.float32)             # (bs, D)
        if ks_ref is not None:
            k = k * ks_ref[0, :, :1]                    # per-slot dequant
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bs)
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = (jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
               + w * block_size)
        # causal inside the ragged segment: row r sits at absolute
        # position qs + r and padding rows (r >= qv) see nothing
        mask = (row < qv) & (col <= row + qs) & (col < ctx)
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = _lanes(alpha * l_ref[:, :1]
                            + jnp.sum(p, axis=-1, keepdims=True))
        v = v_ref[0, 0].astype(jnp.float32)             # (bs, D)
        if vs_ref is not None:
            v = v * vs_ref[0, :, :1]                    # per-slot dequant
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = _lanes(m_new)

    @pl.when(w == w_last)
    def _emit():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_ref[...] / l_safe
        # masked/null rows -> zeros.  Broadcast the f32 stat, never the
        # (bq, 1) predicate: Mosaic lowers a bool broadcast_in_dim
        # through an integer select/compare whose width follows the x64
        # mode at LOWERING time (outside _x32) and aborts on i64
        # ("bitwidth_ <= 32") — see _paged_attn_kernel.
        out = jnp.where(jnp.broadcast_to(l, out.shape) > 0.0, out, 0.0)
        o_ref[...] = out[None].astype(o_ref.dtype)


def _ragged_attn_kernel(bt_ref, cl_ref, sid_ref, qs_ref, qv_ref,
                        q_ref, k_ref, v_ref, o_ref,
                        acc_ref, m_ref, l_ref, **kw):
    _ragged_attn_body(bt_ref, cl_ref, sid_ref, qs_ref, qv_ref,
                      q_ref, k_ref, v_ref, None, None, o_ref,
                      acc_ref, m_ref, l_ref, **kw)


def _ragged_attn_int8_kernel(bt_ref, cl_ref, sid_ref, qs_ref, qv_ref,
                             q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                             acc_ref, m_ref, l_ref, **kw):
    _ragged_attn_body(bt_ref, cl_ref, sid_ref, qs_ref, qv_ref,
                      q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                      acc_ref, m_ref, l_ref, **kw)


@_x32
def ragged_paged_attention(q, k_pool, v_pool, block_tables, context_lens,
                           seq_ids, q_starts, q_valids, block_q=None,
                           scale=None, k_scales=None, v_scales=None):
    """Mixed prefill+decode attention over the paged KV pool.

    q: [T, H, D] flat block-aligned ragged queries (T % block_q == 0);
    k_pool/v_pool: [num_blocks, H, block_size, D];
    block_tables: [S, W] int32; context_lens: [S] int32;
    seq_ids/q_starts/q_valids: [T // block_q] int32 (see module doc;
    ``seq_ids == S`` marks a null/pad q-block).  Returns [T, H, D].

    Int8 pools additionally take ``k_scales``/``v_scales``
    ``[num_blocks, block_size, KV_SCALE_LANES]`` f32 per-slot dequant
    tables (kv_cache.py maintains them through every block lifecycle
    edge); the kernel walks them with the block tables and dequantizes
    in VMEM.
    """
    q, k_pool, v_pool = _demote_f64(q, k_pool, v_pool)
    int8_kv = jnp.dtype(k_pool.dtype) == jnp.dtype(jnp.int8)
    if int8_kv and (k_scales is None or v_scales is None):
        raise ValueError("int8 KV pools need k_scales/v_scales tables")
    T, H, D = q.shape
    if block_q is None:
        block_q = ragged_q_block(q.dtype)
    block_q = int(block_q)
    if T % block_q:
        raise ValueError(f"flat query rows {T} not a multiple of "
                         f"block_q {block_q}")
    nqb = T // block_q
    if seq_ids.shape[0] != nqb:
        raise ValueError(f"{seq_ids.shape[0]} segment descriptors for "
                         f"{nqb} q-blocks")
    num_blocks, _, block_size, _ = k_pool.shape
    S, W = block_tables.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    qt = jnp.swapaxes(q, 0, 1)                          # [H, T, D]
    # null segment: seq_ids == S indexes the appended zero row / zero
    # context so the kernel's guard skips every KV block
    bt = jnp.concatenate(
        [block_tables.astype(jnp.int32),
         jnp.zeros((1, W), jnp.int32)], axis=0)          # [S+1, W]
    cl = jnp.concatenate(
        [context_lens.astype(jnp.int32),
         jnp.zeros((1,), jnp.int32)], axis=0)            # [S+1]
    sid = seq_ids.astype(jnp.int32)
    qs = q_starts.astype(jnp.int32)
    qv = q_valids.astype(jnp.int32)

    q_spec = pl.BlockSpec(
        (1, block_q, D),
        lambda i, h, w, bt, cl, sid, qs, qv: (h, i, 0))
    pool_spec = pl.BlockSpec(
        (1, 1, block_size, D),
        lambda i, h, w, bt, cl, sid, qs, qv: (bt[sid[i], w], h, 0, 0))
    in_specs = [q_spec, pool_spec, pool_spec]
    operands = [qt, k_pool, v_pool]
    kernel = _ragged_attn_kernel
    name = "ragged_attention"
    if int8_kv:
        # the scale blocks ride the same block-table walk as k/v; both
        # trailing dims cover the full scale array so the spec is legal
        scale_spec = pl.BlockSpec(
            (1, block_size, KV_SCALE_LANES),
            lambda i, h, w, bt, cl, sid, qs, qv: (bt[sid[i], w], 0, 0))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales.astype(jnp.float32),
                     v_scales.astype(jnp.float32)]
        kernel = _ragged_attn_int8_kernel
        name = "ragged_attention_int8"

    with _kernel_span(name, "fwd"):
        out = pl.pallas_call(
            functools.partial(
                kernel, block_size=block_size,
                block_q=block_q, scale=float(scale), w_last=W - 1),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=5,
                grid=(nqb, H, W),
                in_specs=in_specs,
                out_specs=pl.BlockSpec(
                    (1, block_q, D),
                    lambda i, h, w, bt, cl, sid, qs, qv: (h, i, 0)),
                scratch_shapes=softmax_scratch(block_q, D),
            ),
            out_shape=jax.ShapeDtypeStruct((H, T, D), q.dtype),
            interpret=_interpret(),
        )(bt, cl, sid, qs, qv, *operands)
    return jnp.swapaxes(out, 0, 1)                      # [T, H, D]


def ragged_block_plan(num_heads, head_dim, block_size, num_q_blocks=4,
                      block_q=None, num_blocks=64, table_width=8,
                      dtype=jnp.float32, kv_dtype=None):
    """The ragged mixed-batch attention block plan (see
    `ragged_paged_attention`).  Scalar-prefetch operands (block tables,
    context lens, segment descriptors) are untiled and omitted, like
    `paged_block_plan`.

    ``kv_dtype=int8`` exports the int8-pool variant: int8 k/v blocks
    plus the two (1, block_size, KV_SCALE_LANES) f32 per-slot scale
    operands; q/out stay ``dtype`` (the compute precision).
    """
    dtype = jnp.dtype(dtype)
    f32 = jnp.dtype(jnp.float32)
    kvdt = jnp.dtype(kv_dtype) if kv_dtype is not None else dtype
    if block_q is None:
        block_q = ragged_q_block(dtype)
    D = head_dim
    T = num_q_blocks * block_q
    pool = (num_blocks, num_heads, block_size, D)
    operands = [
        ("q", (1, block_q, D), (num_heads, T, D), dtype),
        ("k_pool", (1, 1, block_size, D), pool, kvdt),
        ("v_pool", (1, 1, block_size, D), pool, kvdt),
    ]
    if kvdt == jnp.dtype(jnp.int8):
        scales = (num_blocks, block_size, KV_SCALE_LANES)
        operands += [
            ("k_scales", (1, block_size, KV_SCALE_LANES), scales, f32),
            ("v_scales", (1, block_size, KV_SCALE_LANES), scales, f32),
        ]
    operands.append(("out", (1, block_q, D), (num_heads, T, D), dtype))
    return {
        "grid": (num_q_blocks, num_heads, table_width),
        "block_q": block_q,
        "kv_dtype": str(kvdt),
        "operands": operands,
        "scratch": (
            ((block_q, D), f32),
            ((block_q, _STAT_LANES), f32),
            ((block_q, _STAT_LANES), f32),
        ),
    }
