"""Linear algebra ops (paddle.tensor.linalg / paddle.linalg parity).

Reference parity: `python/paddle/tensor/linalg.py` → phi matmul/blas kernels
[UNVERIFIED — empty reference mount].  matmul stays XLA-native: dot_general
maps directly onto the MXU; bf16 inputs with f32 accumulation is the TPU
sweet spot (preferred_element_type below).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ._generated import (  # noqa: F401  (sig-kind rows)
    bmm,
    cholesky,
    cholesky_solve,
    corrcoef,
    cov,
    dot,
    eigh,
    eigvalsh,
    lstsq,
    matmul,
    matrix_exp,
    matrix_power,
    matrix_rank,
    multi_dot,
    mv,
    pinv,
    solve,
    svd,
    triangular_solve,
    vander,
    vecdot,
)

__all__ = [
    "matmul", "mm", "bmm", "dot", "mv", "t", "norm", "dist", "cond",
    "cholesky", "inv", "pinv", "det", "slogdet", "svd", "qr", "eig", "eigh",
    "eigvals", "eigvalsh", "matrix_power", "matrix_rank", "solve",
    "triangular_solve", "cholesky_solve", "lstsq", "lu", "multi_dot",
    "cross", "histogram", "bincount", "einsum", "corrcoef", "cov",
    "householder_product", "matrix_exp", "vecdot", "vander", "pca_lowrank",
    "vector_norm", "matrix_norm", "svdvals", "ormqr",
    "lu_unpack",
]


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def t(input, name=None):
    def impl(v):
        if v.ndim < 2:
            return v
        return jnp.swapaxes(v, -1, -2)

    return dispatch("transpose2", impl, (input,), {})


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def impl(v, *, p, axis, keepdim):
        if p is None:
            p = 2.0 if axis is None or isinstance(axis, int) or (
                isinstance(axis, tuple) and len(axis) == 1) else "fro"
        if p == "fro":
            return jnp.sqrt(jnp.sum(jnp.square(v), axis=axis,
                                    keepdims=keepdim))
        if p == "nuc":
            s = jnp.linalg.svd(v, compute_uv=False)
            return jnp.sum(s, axis=-1, keepdims=keepdim)
        if p == float("inf"):
            return jnp.max(jnp.abs(v), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=axis,
                           keepdims=keepdim)
        return jnp.power(
            jnp.sum(jnp.power(jnp.abs(v), p), axis=axis, keepdims=keepdim),
            1.0 / p)

    ax = axis
    if isinstance(ax, (list, tuple)):
        ax = tuple(int(a) for a in ax)
    elif ax is not None:
        ax = int(ax)
    return dispatch("p_norm", impl, (x,),
                    dict(p=p, axis=ax, keepdim=bool(keepdim)))


def dist(x, y, p=2, name=None):
    from . import math as _m
    return norm(_m.subtract(x, y), p=float(p))


def cond(x, p=None, name=None):
    def impl(v, *, p):
        if p is None or p == 2:
            s = jnp.linalg.svd(v, compute_uv=False)
            return s[..., 0] / s[..., -1]
        return jnp.linalg.norm(v, ord=p, axis=(-2, -1)) * jnp.linalg.norm(
            jnp.linalg.inv(v), ord=p, axis=(-2, -1))

    return dispatch("cond", impl, (x,), dict(p=p))


def inv(x, name=None):
    return dispatch("inverse", jnp.linalg.inv, (x,), {})


inverse = inv


def det(x, name=None):
    return dispatch("determinant", jnp.linalg.det, (x,), {})


def slogdet(x, name=None):
    def impl(v):
        sign, logdet = jnp.linalg.slogdet(v)
        return jnp.stack([sign, logdet])

    return dispatch("slogdeterminant", impl, (x,), {})


def qr(x, mode="reduced", name=None):
    def impl(v, *, mode):
        if mode == "r":
            return (jnp.linalg.qr(v, mode="r"),)
        return tuple(jnp.linalg.qr(v, mode=mode))

    out = dispatch("qr", impl, (x,), dict(mode=mode))
    return out[0] if mode == "r" else out


def eig(x, name=None):
    arr = np.asarray(x._value)
    w, v = np.linalg.eig(arr)
    from ..core.tensor import to_tensor
    return to_tensor(w), to_tensor(v)


def eigvals(x, name=None):
    arr = np.asarray(x._value)
    from ..core.tensor import to_tensor
    return to_tensor(np.linalg.eigvals(arr))


def lu(x, pivot=True, get_infos=False, name=None):
    def impl(v):
        lu_, piv = jax.scipy.linalg.lu_factor(v)
        return lu_, (piv + 1).astype(jnp.int32)

    lu_t, piv = dispatch("lu", impl, (x,), {})
    if get_infos:
        from .creation import zeros
        return lu_t, piv, zeros([1], dtype="int32")
    return lu_t, piv


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack paddle.linalg.lu results into (P, L, U); batched inputs
    vmap over leading dims (lu_factor batches, so must this)."""
    def one(lu_v, piv):
        n, m = lu_v.shape
        k = min(n, m)
        L = jnp.tril(lu_v[:, :k], -1) + jnp.eye(n, k, dtype=lu_v.dtype)
        U = jnp.triu(lu_v[:k, :])
        # pivots (1-based row swaps) -> permutation matrix
        perm = jnp.arange(n)

        def apply_swap(i, perm):
            j = piv[i] - 1
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj)
            return perm.at[j].set(pi)

        perm = jax.lax.fori_loop(0, piv.shape[0], apply_swap, perm)
        P = jnp.eye(n, dtype=lu_v.dtype)[perm].T
        return P, L, U

    def impl(lu_v, piv):
        if lu_v.ndim == 2:
            return one(lu_v, piv)
        batch = lu_v.shape[:-2]
        f = one
        for _ in batch:
            f = jax.vmap(f)
        return f(lu_v, piv)

    return dispatch("lu_unpack", impl, (x, y), {})


def cross(x, y, axis=9, name=None):
    def impl(a, b, *, axis):
        if axis == 9:
            axis = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=axis)

    return dispatch("cross", impl, (x, y), dict(axis=int(axis)))


def histogram(input, bins=100, min=0, max=0, weight=None, density=False,
              name=None):
    arr = np.asarray(input._value)
    lo, hi = (min, max) if (min != 0 or max != 0) else (arr.min(), arr.max())
    h, _ = np.histogram(arr, bins=int(bins), range=(float(lo), float(hi)),
                        weights=None if weight is None else
                        np.asarray(weight._value), density=density)
    from ..core.tensor import to_tensor
    return to_tensor(h if density else h.astype(np.int64))


def bincount(x, weights=None, minlength=0, name=None):
    arr = np.asarray(x._value)
    out = np.bincount(arr,
                      None if weights is None else
                      np.asarray(weights._value),
                      minlength=int(minlength))
    from ..core.tensor import to_tensor
    return to_tensor(out if weights is not None else out.astype(np.int64))


def einsum(equation, *operands):
    ops_ = operands
    if len(ops_) == 1 and isinstance(ops_[0], (list, tuple)):
        ops_ = tuple(ops_[0])
    return dispatch("einsum",
                    lambda *vs, eq: jnp.einsum(eq, *vs), tuple(ops_),
                    dict(eq=equation))


def householder_product(x, tau, name=None):
    def impl(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m)).copy() \
            if a.ndim > 2 else eye
        for i in range(t_.shape[-1]):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[..., i].set(1.0)
            H = jnp.eye(m, dtype=a.dtype) - t_[..., i, None, None] * (
                v[..., :, None] * v[..., None, :])
            q = q @ H
        return q[..., :, :n]

    return dispatch("householder_product", impl, (x, tau), {})


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    def impl(v, *, q, center):
        if center:
            v = v - v.mean(axis=-2, keepdims=True)
        u, s, vt = jnp.linalg.svd(v, full_matrices=False)
        return u[..., :q], s[..., :q], jnp.swapaxes(vt, -1, -2)[..., :q]

    q = q or min(6, x.shape[-2], x.shape[-1])
    return dispatch("pca_lowrank", impl, (x,),
                    dict(q=int(q), center=bool(center)))


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    from ._helpers import _axis as _ax
    return dispatch(
        "vector_norm",
        lambda v, *, p, axis, keepdims: jnp.linalg.vector_norm(
            v, ord=p, axis=axis, keepdims=keepdims),
        (x,), dict(p=float(p), axis=_ax(axis),
                   keepdims=bool(keepdim)))


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    def impl(v, *, p, axis, keepdims):
        a1, a2 = axis
        # normalize the two matrix axes to the trailing positions
        v = jnp.moveaxis(v, (a1 % v.ndim, a2 % v.ndim), (-2, -1))
        out = jnp.linalg.matrix_norm(v, ord=p, keepdims=keepdims)
        if keepdims:
            out = jnp.moveaxis(out, (-2, -1),
                               (a1 % out.ndim, a2 % out.ndim))
        return out

    return dispatch(
        "matrix_norm", impl,
        (x,), dict(p=p if isinstance(p, str) else float(p),
                   axis=tuple(int(a) for a in axis),
                   keepdims=bool(keepdim)))


def svdvals(x, name=None):
    return dispatch("svdvals", jnp.linalg.svdvals, (x,), {})


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """Multiply `other` by the Q of a householder (geqrf) factorization
    (reference: torch/paddle ormqr). Q is materialized via
    householder_product — O(m^2 k) like the reference's blocked apply."""
    def impl(a, t, y, *, left, transpose):
        m, k = a.shape[-2], t.shape[-1]
        a = a[..., :, :k]  # wide geqrf: Q comes from the first k reflectors
        if k < m:
            # the FULL m x m Q: pad with zero reflectors (tau=0 ==
            # identity) so all m columns materialize
            pad_a = [(0, 0)] * (a.ndim - 1) + [(0, m - a.shape[-1])]
            pad_t = [(0, 0)] * (t.ndim - 1) + [(0, m - k)]
            a = jnp.pad(a, pad_a)
            t = jnp.pad(t, pad_t)
        q = jax.lax.linalg.householder_product(a, t)
        if transpose:
            qm = jnp.swapaxes(q, -1, -2)
            if jnp.iscomplexobj(q):  # torch/paddle: conjugate transpose
                qm = jnp.conj(qm)
        else:
            qm = q
        return jnp.matmul(qm, y) if left else jnp.matmul(y, qm)

    return dispatch("ormqr", impl, (x, tau, other),
                    dict(left=bool(left), transpose=bool(transpose)))
