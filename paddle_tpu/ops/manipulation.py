"""Shape/layout manipulation ops (paddle.tensor.manipulation parity).

Reference parity: `python/paddle/tensor/manipulation.py` [UNVERIFIED — empty
reference mount].  Note on TPU idiom: reshape/transpose/slice are free or
near-free under XLA (layout assignment handles them); no view/stride
machinery is needed — Paddle's view semantics are emulated functionally.
"""
from __future__ import annotations

import builtins
import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.dtypes import to_jax_dtype
from ..core.tensor import Tensor, to_tensor
from ._generated import (  # noqa: F401  (sig-kind rows)
    argsort,
    broadcast_to,
    cast,
    clone,
    column_stack,
    concat,
    diagonal,
    flatten,
    flip,
    gather,
    gather_nd,
    index_add,
    index_fill,
    index_put,
    index_sample,
    index_select,
    masked_fill,
    moveaxis,
    reshape,
    roll,
    rot90,
    row_stack,
    scatter,
    scatter_nd,
    scatter_nd_add,
    select_scatter,
    shard_index,
    sort,
    stack,
    swapaxes,
    take_along_axis,
    tile,
    transpose,
    unsqueeze,
)

__all__ = [
    "unflatten",
    "reshape", "reshape_", "transpose", "flatten", "squeeze", "squeeze_",
    "unsqueeze", "unsqueeze_", "concat", "stack", "split", "chunk", "tile",
    "expand", "expand_as", "broadcast_to", "broadcast_tensors", "gather",
    "gather_nd", "scatter", "scatter_", "scatter_nd", "scatter_nd_add",
    "index_select", "index_sample", "index_add", "index_put", "masked_select",
    "masked_fill", "where", "flip", "rot90", "roll", "repeat_interleave",
    "unbind", "take_along_axis", "put_along_axis", "sort", "argsort", "topk",
    "unique", "unique_consecutive", "cast", "getitem", "setitem", "clone",
    "slice", "strided_slice", "crop", "pad", "unstack", "numel", "moveaxis",
    "swapaxes", "as_strided", "view", "view_as", "tensordot", "atleast_1d",
    "atleast_2d", "atleast_3d", "tolist", "flatten_", "unfold",
    "shard_index", "tensor_split", "hsplit", "vsplit", "dsplit",
    "as_complex", "as_real",
    "diagonal", "searchsorted", "bucketize", "index_fill", "masked_scatter", "select_scatter", "slice_scatter", "column_stack", "row_stack",
]


from ._helpers import _int_list  # noqa: F401


def reshape_(x, shape, name=None):
    y = reshape(x, shape)
    x._inplace_update(y._value, y._grad_node, y._out_index)
    return x


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    y = flatten(x, start_axis, stop_axis)
    x._inplace_update(y._value, y._grad_node, y._out_index)
    return x


def squeeze(x, axis=None, name=None):
    def impl(v, *, axis):
        if axis is None:
            return jnp.squeeze(v)
        axes = tuple(a % v.ndim for a in axis)
        axes = tuple(a for a in axes if v.shape[a] == 1)
        return jnp.squeeze(v, axes) if axes else v

    ax = None if axis is None else tuple(_int_list(axis))
    return dispatch("squeeze", impl, (x,), dict(axis=ax))


def squeeze_(x, axis=None, name=None):
    y = squeeze(x, axis)
    x._inplace_update(y._value, y._grad_node, y._out_index)
    return x


def unsqueeze_(x, axis, name=None):
    y = unsqueeze(x, axis)
    x._inplace_update(y._value, y._grad_node, y._out_index)
    return x


def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = _int_list(num_or_sections)
        neg = [i for i, s in enumerate(sections) if s < 0]
        if neg:
            known = sum(s for s in sections if s >= 0)
            sections[neg[0]] = dim - known
    offsets = np.cumsum([0] + sections[:-1]).tolist()

    def impl(v, *, offsets, sections, axis):
        return tuple(
            jax.lax.slice_in_dim(v, o, o + s, axis=axis)
            for o, s in zip(offsets, sections))

    out = dispatch("split", impl, (x,),
                   dict(offsets=tuple(offsets), sections=tuple(sections),
                        axis=axis))
    return builtins.list(out)


def tensor_split(x, num_or_indices, axis=0, name=None):
    dim = x.shape[int(axis)]
    if isinstance(num_or_indices, int):
        n = num_or_indices
        base, rem = divmod(dim, n)
        sections = [base + (1 if i < rem else 0) for i in range(n)]
    else:
        idx = _int_list(num_or_indices)
        sections = []
        prev = 0
        for i in idx:
            sections.append(i - prev)
            prev = i
        sections.append(dim - prev)
    return split(x, sections, axis)


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


def expand(x, shape, name=None):
    shape = _int_list(shape)

    def impl(v, *, shape):
        shape = builtins.list(shape)
        # -1 keeps the original dim; align from the right
        nd = len(shape)
        vshape = [1] * (nd - v.ndim) + builtins.list(v.shape)
        tgt = [vs if s == -1 else s for s, vs in zip(shape, vshape)]
        return jnp.broadcast_to(v.reshape(vshape), tgt)

    return dispatch("expand", impl, (x,), dict(shape=tuple(shape)))


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_tensors(inputs, name=None):
    outs = dispatch("broadcast_tensors",
                    lambda *vs: tuple(jnp.broadcast_arrays(*vs)),
                    tuple(inputs), {})
    return builtins.list(outs)


def scatter_(x, index, updates, overwrite=True, name=None):
    y = scatter(x, index, updates, overwrite)
    x._inplace_update(y._value, y._grad_node, y._out_index)
    return x


def masked_select(x, mask, name=None):
    # dynamic output shape → eager-only (host roundtrip), like Paddle's
    # D2H-sync ops.
    vals = np.asarray(x._value)[np.asarray(mask._value)]
    return to_tensor(vals)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return dispatch("where", lambda c, a, b: jnp.where(c, a, b),
                    (condition, x, y), {})


def nonzero(x, as_tuple=False):
    arr = np.asarray(x._value)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(to_tensor(i.astype(np.int64)) for i in nz)
    return to_tensor(np.stack(nz, axis=1).astype(np.int64))


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        def impl(v, reps, *, axis):
            total = int(np.asarray(reps._value).sum()) if False else None
            return v
        # variable repeats → eager numpy fallback
        arr = np.repeat(np.asarray(x._value), np.asarray(repeats._value),
                        axis=axis)
        return to_tensor(arr)
    return dispatch(
        "repeat_interleave",
        lambda v, *, reps, axis: jnp.repeat(v, reps, axis=axis), (x,),
        dict(reps=int(repeats), axis=None if axis is None else int(axis)))


def unbind(x, axis=0, name=None):
    n = x.shape[int(axis)]

    def impl(v, *, axis, n):
        return tuple(
            jax.lax.index_in_dim(v, i, axis=axis, keepdims=False)
            for i in range(n))

    out = dispatch("unbind", impl, (x,), dict(axis=int(axis), n=n))
    return builtins.list(out)


unstack = unbind


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    def impl(v, idx, val, *, axis, reduce):
        if not isinstance(val, jnp.ndarray):
            val = jnp.asarray(val, v.dtype)
        val = jnp.broadcast_to(val, idx.shape)
        dims = [jnp.arange(s).reshape(
            tuple(s if i == d else 1 for i in range(idx.ndim)))
            for d, s in enumerate(idx.shape)]
        full_idx = tuple(
            idx if d == (axis % v.ndim) else jnp.broadcast_to(
                dims[d], idx.shape)
            for d in range(v.ndim))
        if reduce == "assign":
            return v.at[full_idx].set(val)
        if reduce in ("add", "sum"):
            return v.at[full_idx].add(val)
        if reduce in ("mul", "multiply"):
            return v.at[full_idx].multiply(val)
        if reduce == "amax":
            return v.at[full_idx].max(val)
        if reduce == "amin":
            return v.at[full_idx].min(val)
        raise ValueError(f"unknown reduce {reduce}")

    values_arg = values if isinstance(values, Tensor) else to_tensor(values)
    return dispatch("put_along_axis", impl, (arr, indices, values_arg),
                    dict(axis=int(axis), reduce=reduce))


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    k = int(k.item()) if isinstance(k, Tensor) else int(k)

    def impl(v, *, k, axis, largest):
        ax = axis % v.ndim
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = jax.lax.top_k(vm, k)
        else:
            vals, idx = jax.lax.top_k(-vm, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax),
                jnp.moveaxis(idx, -1, ax).astype(jnp.int64))

    return dispatch("top_k_v2", impl, (x,),
                    dict(k=k, axis=int(axis), largest=bool(largest)))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    arr = np.asarray(x._value)
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    from ..core.dtypes import to_jax_dtype
    idt = to_jax_dtype(dtype)
    if not isinstance(res, tuple):
        return to_tensor(res)
    outs = [to_tensor(res[0])]
    i = 1
    if return_index:
        outs.append(to_tensor(res[i].astype(idt))); i += 1
    if return_inverse:
        outs.append(to_tensor(res[i].astype(idt))); i += 1
    if return_counts:
        outs.append(to_tensor(res[i].astype(idt))); i += 1
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, dtype="int64", name=None):
    arr = np.asarray(x._value)
    if axis is None:
        arr = arr.reshape(-1)
        axis = 0
    keep = np.ones(arr.shape[axis], dtype=bool)
    sliced = np.moveaxis(arr, axis, 0)
    keep[1:] = np.any(
        sliced[1:].reshape(sliced.shape[0] - 1, -1) !=
        sliced[:-1].reshape(sliced.shape[0] - 1, -1), axis=1)
    out = np.moveaxis(sliced[keep], 0, axis)
    from ..core.dtypes import to_jax_dtype
    idt = to_jax_dtype(dtype)
    outs = [to_tensor(out)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(to_tensor(inv.astype(idt)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, arr.shape[axis]))
        outs.append(to_tensor(counts.astype(idt)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def _canon_index(idx):
    """Convert Tensors inside an index tuple to raw arrays (traced ok)."""
    from ..core.tensor import Tensor as T

    def conv(i):
        if isinstance(i, T):
            return i.value()
        if isinstance(i, (builtins.list, np.ndarray)):
            return jnp.asarray(i)
        return i

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


def getitem(x, idx):
    cidx = _canon_index(idx)
    try:  # fully-static index → attr (cacheable in the eager jit cache)
        from ..core.dispatch import _static_sig
        _static_sig(cidx)

        def impl_static(v, *, cidx):
            return v[cidx]

        return dispatch("slice", impl_static, (x,), dict(cidx=cidx))
    except TypeError:
        pass  # index contains arrays: keep them in the closure

    def impl(v):
        return v[cidx]

    return dispatch("slice", impl, (x,), {})


def setitem(x, idx, value):
    if (not x.stop_gradient) and x._grad_node is None and \
            __import__("paddle_tpu.core.autograd", fromlist=["x"]
                       ).is_grad_enabled():
        # Paddle allows inplace on leaf only when it doesn't require grad
        # tracking... we mirror torch/paddle: disallow on leaf param.
        pass
    cidx = _canon_index(idx)
    if isinstance(value, Tensor):
        def impl(v, val):
            return v.at[cidx].set(jnp.asarray(val, v.dtype))
        y = dispatch("set_value", impl, (x, value), {})
    else:
        def impl(v):
            return v.at[cidx].set(jnp.asarray(value, v.dtype))
        y = dispatch("set_value", impl, (x,), {})
    x._inplace_update(y._value, y._grad_node, y._out_index)
    return x


def slice(input, axes, starts, ends):
    idx = [builtins.slice(None)] * input.ndim
    for a, s, e in zip(_int_list(axes), _int_list(starts), _int_list(ends)):
        idx[a] = builtins.slice(s, e)
    return getitem(input, tuple(idx))


def strided_slice(x, axes, starts, ends, strides, name=None):
    idx = [builtins.slice(None)] * x.ndim
    for a, s, e, st in zip(_int_list(axes), _int_list(starts),
                           _int_list(ends), _int_list(strides)):
        idx[a] = builtins.slice(s, e, st)
    return getitem(x, tuple(idx))


def crop(x, shape=None, offsets=None, name=None):
    shape = _int_list(shape)
    offsets = _int_list(offsets) if offsets is not None else [0] * x.ndim
    idx = tuple(builtins.slice(o, o + (s if s != -1 else x.shape[i] - o))
                for i, (o, s) in enumerate(zip(offsets, shape)))
    return getitem(x, idx)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..nn import functional as F
    return F.pad(x, pad, mode=mode, value=value, data_format=data_format)


def numel(x, name=None):
    return to_tensor(int(np.prod(x.shape)) if x.shape else 1, dtype="int64")


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (builtins.list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def as_strided(x, shape, stride, offset=0, name=None):
    arr = np.lib.stride_tricks.as_strided(
        np.asarray(x._value).reshape(-1)[offset:],
        shape=tuple(shape),
        strides=tuple(s * x._value.dtype.itemsize for s in stride))
    return to_tensor(arr.copy())


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, Tensor):
        axes = axes.numpy().tolist()

    def impl(a, b, *, axes):
        if isinstance(axes, builtins.list):
            axes = tuple(tuple(ax) for ax in axes)
        return jnp.tensordot(a, b, axes=axes)

    return dispatch("tensordot", impl, (x, y), dict(axes=axes))


def atleast_1d(*inputs, name=None):
    outs = [reshape(i, [1]) if i.ndim == 0 else i for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = []
    for i in inputs:
        if i.ndim == 0:
            outs.append(reshape(i, [1, 1]))
        elif i.ndim == 1:
            outs.append(unsqueeze(i, 0))
        else:
            outs.append(i)
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = []
    for i in inputs:
        o = atleast_2d(i)
        if isinstance(o, builtins.list):
            o = o[0]
        outs.append(unsqueeze(o, -1) if o.ndim == 2 else o)
    return outs[0] if len(outs) == 1 else outs


def tolist(x):
    return x.numpy().tolist()


def unfold(x, axis, size, step, name=None):
    n = (x.shape[axis] - size) // step + 1

    def impl(v, *, axis, size, step, n):
        idx = jnp.arange(n) * step
        slices = [jax.lax.dynamic_slice_in_dim(v, int(i), size, axis)
                  for i in range(0, n * step, step)]
        return jnp.stack(slices, axis=axis if False else -2) if False else \
            jnp.stack([jax.lax.slice_in_dim(v, i * step, i * step + size,
                                            axis=axis)
                       for i in range(n)], axis=axis)

    def impl2(v, *, axis, size, step, n):
        parts = [jax.lax.slice_in_dim(v, i * step, i * step + size, axis=axis)
                 for i in range(n)]
        stacked = jnp.stack(parts, axis=axis)
        return jnp.moveaxis(stacked, axis + 1, -1)

    return dispatch("unfold", impl2, (x,),
                    dict(axis=int(axis), size=int(size), step=int(step), n=n))


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    def impl(seq, vals, right, out_int32):
        side = "right" if right else "left"
        if seq.ndim == 1:
            out = jnp.searchsorted(seq, vals, side=side)
        else:
            # batched rows: vmap over all leading dims
            flat_seq = seq.reshape(-1, seq.shape[-1])
            flat_vals = vals.reshape(-1, vals.shape[-1])
            out = jax.vmap(
                lambda s, v: jnp.searchsorted(s, v, side=side))(
                flat_seq, flat_vals).reshape(vals.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)

    return dispatch("searchsorted", impl, (sorted_sequence, values),
                    dict(right=bool(right), out_int32=bool(out_int32)),
                    differentiable=False)


def bucketize(x, sorted_sequence, out_int32=False, right=False,
              name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32,
                        right=right)


def masked_scatter(x, mask, value, name=None):
    """Fill True positions of mask (in order) with value's elements."""
    try:  # eager: enough source elements? (traced masks skip the check)
        needed = int(np.asarray(
            mask._value if hasattr(mask, "_value") else mask).sum())
        have = int(np.prod(np.asarray(
            value._value if hasattr(value, "_value") else value).shape))
        if have < needed:
            raise ValueError(
                f"masked_scatter: value has {have} elements but mask "
                f"selects {needed}")
    except (TypeError, jax.errors.ConcretizationTypeError):
        pass

    def impl(v, m, val):
        m = jnp.broadcast_to(m, v.shape)
        flat_v = v.reshape(-1)
        flat_m = m.reshape(-1)
        # k-th True position takes value.flatten()[k]
        order = jnp.cumsum(flat_m.astype(jnp.int32)) - 1
        src = val.reshape(-1)
        take = jnp.clip(order, 0, src.shape[0] - 1)
        return jnp.where(flat_m, src[take], flat_v).reshape(v.shape)

    return dispatch("masked_scatter", impl, (x, mask, value), {})


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def impl(v, src, axes, starts, ends, strides):
        idx = [builtins.slice(None)] * v.ndim  # `slice` op shadows builtin
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins.slice(s, e, st)
        return v.at[tuple(idx)].set(src)

    return dispatch("slice_scatter", impl, (x, value),
                    dict(axes=tuple(axes), starts=tuple(starts),
                         ends=tuple(ends), strides=tuple(strides)))


def unflatten(x, axis, shape, name=None):
    """Split one axis into the given shape (paddle.unflatten; the
    nn.Unflatten layer's functional form).  One -1 entry infers."""
    axis = int(axis)
    shape = [int(s) for s in shape]
    n = x.shape[axis if axis >= 0 else x.ndim + axis]
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[shape.index(-1)] = n // known
    new_shape = list(x.shape)
    ax = axis if axis >= 0 else len(new_shape) + axis
    new_shape[ax:ax + 1] = shape
    return reshape(x, new_shape)


def as_complex(x, name=None):
    """[..., 2] real pairs -> complex (paddle.as_complex)."""
    if x.shape[-1] != 2:
        raise ValueError(
            f"as_complex: the last dimension must be exactly 2 (got "
            f"{x.shape[-1]})")
    return dispatch(
        "as_complex",
        lambda v: jax.lax.complex(v[..., 0], v[..., 1]), (x,), {})


def as_real(x, name=None):
    """complex -> [..., 2] real pairs (paddle.as_real)."""
    return dispatch(
        "as_real",
        lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1),
        (x,), {})
