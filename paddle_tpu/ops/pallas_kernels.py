"""Pallas TPU kernels for the hot op set.

Reference parity: the reference implements these as hand-written CUDA in
`paddle/phi/kernels/gpu/` — `flash_attn_kernel.cu` (wrapping
third_party/flashattn), `layer_norm_kernel.cu`, `rms_norm_kernel.cu`,
`c_softmax_with_cross_entropy_op.cu` [UNVERIFIED — empty reference mount;
upstream-layout paths per SURVEY.md §2.1].

TPU-native design: each kernel is a `pl.pallas_call` tiled for the MXU/VPU
(blocks of 128 lanes, f32 accumulation in VMEM) wrapped in
`jax.custom_vjp` so both the eager tape (jax.vjp in core/dispatch.py) and
`to_static` (jax.jit) differentiate through the hand-written backward.

On non-TPU backends (tests run on XLA-CPU) the same kernels execute in
Pallas interpret mode, so numerics are validated everywhere the suite
runs; on TPU they compile via Mosaic.

Mosaic block-mapping rules honoured here (the round-2 kernels violated
them and failed to compile on hardware): the last two dims of every
BlockSpec must each be divisible by (8, 128) or equal to the overall
array dim.  Consequently:
  * every array crossing the pallas_call boundary is rank >= 2;
  * per-row statistics (lse, mean, rstd, loss, delta, incoming
    cotangents, integer labels) travel as f32/int32 arrays with a
    trailing `_STAT_LANES == 8` lane dim — written as lane-broadcasts,
    read back via `[:, :1]` (8 == the array dim satisfies the lane
    rule; only 8x memory on arrays that are tiny to begin with);
  * in-kernel reductions keep dims (`keepdims=True`) so all VPU values
    stay rank-2;
  * dgamma/dbeta are reduced with the sequential-grid accumulation
    pattern: one (8, N) output block revisited by every program,
    zero-initialised under `pl.when(program_id == 0)`.

Layout conventions:
  * attention layout inside the kernels is [batch*heads, seq, head_dim]
    (callers convert from Paddle's [B, S, H, D]);
  * sequence dims are padded to a multiple of the block size here, with
    padding masked inside the kernels (cols → -inf, padded lse → +inf);
  * all softmax/variance math runs in float32 regardless of input dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# pltpu is importable on CPU builds of jax as well; the VMEM scratch
# accumulators in the xent kernels require it even in interpret mode
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "flash_attention",
    "flash_block_plan",
    "fused_layer_norm",
    "fused_rms_norm",
    "fused_softmax_cross_entropy",
    "paged_attention",
    "paged_block_plan",
]

# Shared tile primitives (see ops/pallas_tiles.py): tracing policy,
# dtype-aware block picking, stat-lane layout, padding.  These names are
# re-exported here so downstream `from .pallas_kernels import _x32, ...`
# keeps binding the SAME objects — the refactor's bit-identity contract.
from .pallas_tiles import (_NEG_INF, _STAT_LANES, _demote_f64,
                           _interpret, _kernel_span, _lanes,
                           _ln_block_rows, _min_rows, _pad_dim,
                           _round_up, _sane_block, _x32, _xent_blocks,
                           softmax_scratch, stat_scratch)


# =====================================================================
# Flash attention
# =====================================================================

def _attn_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                     scale, causal, block_k, sk_real, offset):
    """One (batch*head, q-block) program: online-softmax over K blocks."""
    q = q_ref[0].astype(jnp.float32)                     # (block_q, D)
    block_q, _ = q.shape
    sk_pad = k_ref.shape[1]
    q_start = pl.program_id(1) * block_q

    num_k_blocks = sk_pad // block_k
    if causal:
        # highest kv index any row in this q block may attend to
        hi = q_start + block_q + offset
        num_k_blocks = jnp.minimum(
            num_k_blocks, (jnp.maximum(hi, 0) + block_k - 1) // block_k)

    def body(i, carry):
        m_prev, l_prev, acc = carry                       # (bq,1)x2,(bq,D)
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + i * block_k
        mask = col < sk_real                              # K padding
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + q_start
            mask = jnp.logical_and(mask, col <= row + offset)
        s = jnp.where(mask, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # explicit zero on masked cols: for a fully-masked row s == m_new
        # == _NEG_INF and exp(s - m_new) would be 1, not 0
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_k_blocks, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(l_safe))  # (bq, 1)
    lse_ref[0] = jnp.broadcast_to(lse, (block_q, _STAT_LANES))


def _attn_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, *, scale, causal, block_k, sk_real, offset):
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]                               # (bq, 1)
    delta = delta_ref[0][:, :1]
    block_q = q.shape[0]
    sk_pad = k_ref.shape[1]
    q_start = pl.program_id(1) * block_q

    num_k_blocks = sk_pad // block_k
    if causal:
        hi = q_start + block_q + offset
        num_k_blocks = jnp.minimum(
            num_k_blocks, (jnp.maximum(hi, 0) + block_k - 1) // block_k)

    def body(i, dq):
        k_blk = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + i * block_k
        mask = col < sk_real
        if causal:
            row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + q_start
            mask = jnp.logical_and(mask, col <= row + offset)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                              # (bq, bk)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq0 = jnp.zeros_like(q)
    dq = jax.lax.fori_loop(0, num_k_blocks, body, dq0)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dk_ref, dv_ref, *, scale, causal, block_q,
                         sq_real, offset):
    k = k_ref[0].astype(jnp.float32)                     # (block_k, D)
    v = v_ref[0].astype(jnp.float32)
    block_k = k.shape[0]
    sq_pad = q_ref.shape[1]
    k_start = pl.program_id(1) * block_k

    lo = 0
    num_q_blocks = sq_pad // block_q
    if causal:
        # first q row that can see this k block: row >= k_start - offset
        lo = jnp.maximum(k_start - offset, 0) // block_q

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32)
        lse_blk = lse_ref[0, pl.ds(i * block_q, block_q), :][:, :1]
        delta_blk = delta_ref[0, pl.ds(i * block_q, block_q), :][:, :1]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + i * block_q
        mask = row < sq_real
        if causal:
            col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + k_start
            mask = jnp.logical_and(mask, col <= row + offset)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse_blk)
        dv = dv + jax.lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk) * scale
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros_like(k)
    dv0 = jnp.zeros_like(v)
    dk, dv = jax.lax.fori_loop(lo, num_q_blocks, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


@_x32
def _flash_fwd(q, k, v, scale, causal, sq_real, sk_real, block_q, block_k):
    bh, sq_pad, d = q.shape
    sk_pad = k.shape[1]
    offset = sk_real - sq_real  # causal alignment for cross-length attn
    grid = (bh, sq_pad // block_q)
    with _kernel_span("flash_attention", "fwd"):
        out, lse = pl.pallas_call(
        functools.partial(_attn_fwd_kernel, scale=scale, causal=causal,
                          block_k=block_k, sk_real=sk_real, offset=offset),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _STAT_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_pad, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq_pad, _STAT_LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse


@_x32
def _flash_bwd(q, k, v, do, out, lse, scale, causal, sq_real, sk_real,
               block_q, block_k):
    """lse arrives in the (BH, Sq_pad, _STAT_LANES) stat-lane layout."""
    bh, sq_pad, d = q.shape
    sk_pad = k.shape[1]
    offset = sk_real - sq_real
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)              # (BH, Sq_pad, 1)
    delta = jnp.broadcast_to(delta, (bh, sq_pad, _STAT_LANES))
    # p = exp(s - lse) must be 0 wherever a row has no visible keys:
    # padded q rows AND real rows the causal mask empties (Sq > Sk case,
    # forward stored lse = _NEG_INF there).  Force lse huge so exp → 0.
    row = jnp.arange(sq_pad)[None, :, None]
    empty = jnp.logical_or(row >= sq_real, lse <= _NEG_INF / 2)
    lse_safe = jnp.where(empty, jnp.float32(1e30), lse)
    with _kernel_span("flash_attention", "bwd_dq"):
        dq = pl.pallas_call(
        functools.partial(_attn_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, sk_real=sk_real, offset=offset),
        grid=(bh, sq_pad // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, sk_pad, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _STAT_LANES), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _STAT_LANES), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq_pad, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse_safe, delta)
    with _kernel_span("flash_attention", "bwd_dkv"):
        dk, dv = pl.pallas_call(
        functools.partial(_attn_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, sq_real=sq_real, offset=offset),
        grid=(bh, sk_pad // block_k),
        in_specs=[
            pl.BlockSpec((1, sq_pad, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, sq_pad, d), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq_pad, _STAT_LANES), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, sq_pad, _STAT_LANES), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk_pad, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk_pad, d), v.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse_safe, delta)
    return dq, dk, dv


_autotune_table = None


def autotune_cache_path():
    import os
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))),
        ".bench_cache", "flash_blocks.json")


def _load_autotune():
    """Flash block-size autotune cache (the reference's
    phi/kernels/autotune role): scripts/flash_block_sweep.py measures
    the (block_q, block_k) grid on the real chip in a healthy window and
    persists the winners; runtime consults them by sequence length.
    TPU only — interpret-mode tests must not change tiling based on a
    local tuning file."""
    global _autotune_table
    if _autotune_table is None:
        if _interpret():
            _autotune_table = {}
            return _autotune_table
        import json
        try:
            _autotune_table = {
                int(k): (int(v[0]), int(v[1]))
                for k, v in json.load(
                    open(autotune_cache_path())).items()}
        except Exception:
            _autotune_table = {}
    return _autotune_table


def set_flash_block_sizes(block_q=None, block_k=None):
    """Process-wide override for the sweep harness."""
    global _block_override
    _block_override = (block_q, block_k)


_block_override = (None, None)


def _pick_block(seq: int, which: int = 0, dtype=jnp.float32) -> int:
    """Q/K block rows for `seq`: legal by construction for `dtype`
    (sublane multiple of _min_rows), covering `seq` after _round_up
    padding.  Overrides and autotuned values are clamped to legality
    rather than trusted — an illegal sweep value degrades to the
    default instead of crashing Mosaic."""
    mr = _min_rows(dtype)
    ov = _sane_block(_block_override[which], seq, mr)
    if ov:
        return ov
    tuned = _load_autotune().get(seq)
    if tuned:
        t = _sane_block(tuned[which], seq, mr)
        if t:
            return t
    return 128 if seq >= 128 else _round_up(max(seq, mr), mr)


def flash_block_plan(batch, seq_q, seq_k, heads, head_dim,
                     dtype=jnp.float32, direction="fwd"):
    """The exact block plan the flash kernels use for these shapes.

    ``direction`` selects the pallas_call being described: ``"fwd"``
    (`_flash_fwd`), ``"bwd_dq"`` (the dq pass of `_flash_bwd`) or
    ``"bwd_dkv"`` (its dk/dv pass).  Returns grid, chosen block sizes,
    and per-operand (name, block_shape, padded_array_shape, dtype)
    tuples in pallas_call order — the input
    `analysis.tiling.check_pallas_call` validates statically (and the
    gate uses to diagnose probe failures).  Keep in lockstep with the
    kernel builders' specs.
    """
    dtype = jnp.dtype(dtype)
    block_q = _pick_block(seq_q, 0, dtype)
    block_k = _pick_block(seq_k, 1, dtype)
    bh = batch * heads
    sq_pad = _round_up(seq_q, block_q)
    sk_pad = _round_up(seq_k, block_k)
    d = head_dim
    f32 = jnp.dtype(jnp.float32)
    base = {
        "direction": direction,
        "block_q": block_q,
        "block_k": block_k,
        "scratch": (),
    }
    q_blk = ("q", (1, block_q, d), (bh, sq_pad, d), dtype)
    q_full = ("q", (1, sq_pad, d), (bh, sq_pad, d), dtype)
    k_blk = ("k", (1, block_k, d), (bh, sk_pad, d), dtype)
    k_full = ("k", (1, sk_pad, d), (bh, sk_pad, d), dtype)
    v_blk = ("v", (1, block_k, d), (bh, sk_pad, d), dtype)
    v_full = ("v", (1, sk_pad, d), (bh, sk_pad, d), dtype)
    stat_blk = lambda name: (  # noqa: E731 - local table helper
        name, (1, block_q, _STAT_LANES), (bh, sq_pad, _STAT_LANES), f32)
    stat_full = lambda name: (  # noqa: E731
        name, (1, sq_pad, _STAT_LANES), (bh, sq_pad, _STAT_LANES), f32)
    if direction == "fwd":
        base["grid"] = (bh, sq_pad // block_q)
        base["operands"] = [
            q_blk, k_full, v_full,
            ("out", (1, block_q, d), (bh, sq_pad, d), dtype),
            stat_blk("lse"),
        ]
    elif direction == "bwd_dq":
        base["grid"] = (bh, sq_pad // block_q)
        base["operands"] = [
            q_blk, k_full, v_full,
            ("do", (1, block_q, d), (bh, sq_pad, d), dtype),
            stat_blk("lse"), stat_blk("delta"),
            ("dq", (1, block_q, d), (bh, sq_pad, d), dtype),
        ]
    elif direction == "bwd_dkv":
        base["grid"] = (bh, sk_pad // block_k)
        base["operands"] = [
            q_full, k_blk, v_blk,
            ("do", (1, sq_pad, d), (bh, sq_pad, d), dtype),
            stat_full("lse"), stat_full("delta"),
            ("dk", (1, block_k, d), (bh, sk_pad, d), dtype),
            ("dv", (1, block_k, d), (bh, sk_pad, d), dtype),
        ]
    else:
        raise ValueError(
            f"direction must be fwd|bwd_dq|bwd_dkv, got {direction!r}")
    return base


def paged_block_plan(num_heads, head_dim, block_size, num_blocks=64,
                     batch=1, table_width=8, dtype=jnp.float32):
    """The paged decode-attention block plan (see `paged_attention`)."""
    dtype = jnp.dtype(dtype)
    f32 = jnp.dtype(jnp.float32)
    D = head_dim
    pool = (num_blocks, num_heads, block_size, D)
    return {
        "grid": (batch, num_heads, table_width),
        "operands": [
            ("q", (1, 1, 1, D), (batch, num_heads, 1, D), dtype),
            ("k_pool", (1, 1, block_size, D), pool, dtype),
            ("v_pool", (1, 1, block_size, D), pool, dtype),
            ("out", (1, 1, 1, D), (batch, num_heads, 1, D), dtype),
        ],
        "scratch": (
            ((_STAT_LANES, D), f32),
            ((_STAT_LANES, _STAT_LANES), f32),
            ((_STAT_LANES, _STAT_LANES), f32),
        ),
    }


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_bhsd(q, k, v, scale, causal):
    out, _ = _flash_attention_bhsd_fwd(q, k, v, scale, causal)
    return out


def _flash_attention_bhsd_fwd(q, k, v, scale, causal):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, 0, q.dtype)
    block_k = _pick_block(sk, 1, q.dtype)
    qp = _pad_dim(q, 1, _round_up(sq, block_q))
    kp = _pad_dim(k, 1, _round_up(sk, block_k))
    vp = _pad_dim(v, 1, _round_up(sk, block_k))
    out, lse = _flash_fwd(qp, kp, vp, scale, causal, sq, sk,
                          block_q, block_k)
    return out[:, :sq], (q, k, v, out, lse)


def _flash_attention_bhsd_bwd(scale, causal, res, g):
    q, k, v, out_pad, lse = res
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = _pick_block(sq, 0, q.dtype)
    block_k = _pick_block(sk, 1, q.dtype)
    qp = _pad_dim(q, 1, _round_up(sq, block_q))
    kp = _pad_dim(k, 1, _round_up(sk, block_k))
    vp = _pad_dim(v, 1, _round_up(sk, block_k))
    gp = _pad_dim(g, 1, _round_up(sq, block_q))
    dq, dk, dv = _flash_bwd(qp, kp, vp, gp, out_pad, lse, scale, causal,
                            sq, sk, block_q, block_k)
    return dq[:, :sq], dk[:, :sk], dv[:, :sk]


_flash_attention_bhsd.defvjp(_flash_attention_bhsd_fwd,
                             _flash_attention_bhsd_bwd)


def flash_attention(q, k, v, *, causal=False, scale=None):
    """Flash attention over Paddle layout [B, S, H, D]; differentiable.

    Online-softmax tiled for the MXU with a hand-written flash backward
    (the reference's flash_attn_kernel.cu + flash_attn_grad role).
    Supports head_dim not a multiple of 128 (Mosaic pads lanes), uneven
    sequence lengths (padded + masked here), causal cross-attention
    (Sk != Sq aligned bottom-right, matching flash-attn semantics).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    q, k, v = _demote_f64(q, k, v)
    b, sq, h, d = q.shape
    sk = k.shape[1]
    qt = jnp.swapaxes(q, 1, 2).reshape(b * h, sq, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * h, sk, d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * h, sk, d)
    out = _flash_attention_bhsd(qt, kt, vt, float(scale), bool(causal))
    return jnp.swapaxes(out.reshape(b, h, sq, d), 1, 2)


# =====================================================================
# Fused layer norm / rms norm
# =====================================================================

def _ln_fwd_kernel(x_ref, g_ref, b_ref, o_ref, mu_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)                    # (block_rows, N)
    br = x.shape[0]
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    o_ref[:] = (xhat * g_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)
    mu_ref[:] = jnp.broadcast_to(mu, (br, _STAT_LANES))
    rstd_ref[:] = jnp.broadcast_to(rstd, (br, _STAT_LANES))


def _ln_bwd_kernel(x_ref, g_ref, mu_ref, rstd_ref, do_ref,
                   dx_ref, dg_ref, db_ref):
    x = x_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    gamma = g_ref[:].astype(jnp.float32)                # (1, N)
    mu = mu_ref[:][:, :1]
    rstd = rstd_ref[:][:, :1]
    xhat = (x - mu) * rstd

    # dgamma/dbeta: sequential-grid accumulation into one revisited block
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    dg = jnp.sum(do * xhat, axis=0, keepdims=True)      # (1, N)
    db = jnp.sum(do, axis=0, keepdims=True)
    dg_ref[:] = dg_ref[:] + jnp.broadcast_to(dg, dg_ref.shape)
    db_ref[:] = db_ref[:] + jnp.broadcast_to(db, db_ref.shape)

    dxhat = do * gamma
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = (dxhat - m1 - xhat * m2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_layer_norm_2d(x, gamma, beta, eps):
    return _fused_layer_norm_2d_fwd(x, gamma, beta, eps)[0]


@_x32
def _fused_layer_norm_2d_fwd(x, gamma, beta, eps):
    rows, n = x.shape
    br = _ln_block_rows(rows, n)
    rows_pad = _round_up(rows, br)
    xp = _pad_dim(x, 0, rows_pad)
    with _kernel_span("layer_norm", "fwd"):
        out, mu, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(rows_pad // br,),
        in_specs=[
            pl.BlockSpec((br, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, n), lambda i: (i, 0)),
            pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_pad, n), x.dtype),
            jax.ShapeDtypeStruct((rows_pad, _STAT_LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows_pad, _STAT_LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(xp, gamma.reshape(1, n), beta.reshape(1, n))
    return out[:rows], (x, gamma, mu, rstd)


@_x32
def _fused_layer_norm_2d_bwd(eps, res, do):
    x, gamma, mu, rstd = res
    rows, n = x.shape
    br = _ln_block_rows(rows, n)
    rows_pad = _round_up(rows, br)
    nb = rows_pad // br
    xp = _pad_dim(x, 0, rows_pad)
    dop = _pad_dim(do, 0, rows_pad)
    with _kernel_span("layer_norm", "bwd"):
        dx, dg_acc, db_acc = pl.pallas_call(
        _ln_bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, n), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, n), lambda i: (i, 0)),
            pl.BlockSpec((8, n), lambda i: (0, 0)),
            pl.BlockSpec((8, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_pad, n), x.dtype),
            jax.ShapeDtypeStruct((8, n), jnp.float32),
            jax.ShapeDtypeStruct((8, n), jnp.float32),
        ],
        interpret=_interpret(),
    )(xp, gamma.reshape(1, n), mu, rstd, dop)
    dgamma = dg_acc[0].astype(gamma.dtype)
    dbeta = db_acc[0].astype(gamma.dtype)
    return dx[:rows], dgamma, dbeta


_fused_layer_norm_2d.defvjp(_fused_layer_norm_2d_fwd,
                            _fused_layer_norm_2d_bwd)


def fused_layer_norm(x, gamma, beta, eps=1e-5):
    """LayerNorm over the last dim, any leading shape; differentiable."""
    x, gamma, beta = _demote_f64(x, gamma, beta)
    shape = x.shape
    n = shape[-1]
    out = _fused_layer_norm_2d(x.reshape(-1, n), gamma, beta, float(eps))
    return out.reshape(shape)


def _rms_fwd_kernel(x_ref, g_ref, o_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    br = x.shape[0]
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    o_ref[:] = (x * rstd * g_ref[:].astype(jnp.float32)).astype(
        o_ref.dtype)
    rstd_ref[:] = jnp.broadcast_to(rstd, (br, _STAT_LANES))


def _rms_bwd_kernel(x_ref, g_ref, rstd_ref, do_ref, dx_ref, dg_ref):
    x = x_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    gamma = g_ref[:].astype(jnp.float32)                # (1, N)
    rstd = rstd_ref[:][:, :1]
    xhat = x * rstd

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[:] = jnp.zeros_like(dg_ref)

    dg = jnp.sum(do * xhat, axis=0, keepdims=True)
    dg_ref[:] = dg_ref[:] + jnp.broadcast_to(dg, dg_ref.shape)

    dxhat = do * gamma
    m = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = (dxhat - xhat * m) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fused_rms_norm_2d(x, gamma, eps):
    return _fused_rms_norm_2d_fwd(x, gamma, eps)[0]


@_x32
def _fused_rms_norm_2d_fwd(x, gamma, eps):
    rows, n = x.shape
    br = _ln_block_rows(rows, n)
    rows_pad = _round_up(rows, br)
    xp = _pad_dim(x, 0, rows_pad)
    with _kernel_span("rms_norm", "fwd"):
        out, rstd = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(rows_pad // br,),
        in_specs=[
            pl.BlockSpec((br, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, n), lambda i: (i, 0)),
            pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_pad, n), x.dtype),
            jax.ShapeDtypeStruct((rows_pad, _STAT_LANES), jnp.float32),
        ],
        interpret=_interpret(),
    )(xp, gamma.reshape(1, n))
    return out[:rows], (x, gamma, rstd)


@_x32
def _fused_rms_norm_2d_bwd(eps, res, do):
    x, gamma, rstd = res
    rows, n = x.shape
    br = _ln_block_rows(rows, n)
    rows_pad = _round_up(rows, br)
    nb = rows_pad // br
    xp = _pad_dim(x, 0, rows_pad)
    dop = _pad_dim(do, 0, rows_pad)
    with _kernel_span("rms_norm", "bwd"):
        dx, dg_acc = pl.pallas_call(
        _rms_bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((br, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((br, _STAT_LANES), lambda i: (i, 0)),
            pl.BlockSpec((br, n), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, n), lambda i: (i, 0)),
            pl.BlockSpec((8, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_pad, n), x.dtype),
            jax.ShapeDtypeStruct((8, n), jnp.float32),
        ],
        interpret=_interpret(),
    )(xp, gamma.reshape(1, n), rstd, dop)
    dgamma = dg_acc[0].astype(gamma.dtype)
    return dx[:rows], dgamma


_fused_rms_norm_2d.defvjp(_fused_rms_norm_2d_fwd, _fused_rms_norm_2d_bwd)


def fused_rms_norm(x, gamma, eps=1e-6):
    """RMSNorm over the last dim, any leading shape; differentiable."""
    x, gamma = _demote_f64(x, gamma)
    shape = x.shape
    n = shape[-1]
    out = _fused_rms_norm_2d(x.reshape(-1, n), gamma, float(eps))
    return out.reshape(shape)


# =====================================================================
# Fused softmax cross-entropy (from logits + integer labels)
# =====================================================================

def _xent_fwd_kernel(x_ref, lbl_ref, loss_ref, lse_ref,
                     m_acc, l_acc, pick_acc, *, block_v):
    """Online logsumexp over vocab blocks.

    Grid is (row_blocks, vocab_blocks) with the vocab dim minor, so for a
    fixed row block the vocab programs run sequentially and the VMEM
    scratch accumulators (running max / sum-exp / picked logit) persist
    across them.  VMEM use is O(block_rows * block_v) regardless of the
    full vocab size — round 2's full-row (br, V) blocks OOMed scoped VMEM
    at V=30k in the backward (BENCH_r02/r03 crash).
    """
    j = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)                   # (block_rows, bv)
    br = x.shape[0]
    lbl = lbl_ref[:][:, :1]                            # (block_rows, 1)

    @pl.when(j == 0)
    def _():
        m_acc[:] = jnp.full((br, _STAT_LANES), _NEG_INF, jnp.float32)
        l_acc[:] = jnp.zeros((br, _STAT_LANES), jnp.float32)
        pick_acc[:] = jnp.zeros((br, _STAT_LANES), jnp.float32)

    m_prev = m_acc[:][:, :1]
    m_blk = jnp.max(x, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_blk)
    l_new = (l_acc[:][:, :1] * jnp.exp(m_prev - m_new)
             + jnp.sum(jnp.exp(x - m_new), axis=-1, keepdims=True))
    col = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1) + j * block_v
    picked = jnp.sum(jnp.where(col == lbl, x, 0.0), axis=-1, keepdims=True)
    m_acc[:] = jnp.broadcast_to(m_new, (br, _STAT_LANES))
    l_acc[:] = jnp.broadcast_to(l_new, (br, _STAT_LANES))
    pick_acc[:] += jnp.broadcast_to(picked, (br, _STAT_LANES))

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        lse = m_acc[:][:, :1] + jnp.log(l_acc[:][:, :1])
        # ignore_index rows (lbl < 0) produce 0 loss
        valid = lbl >= 0
        loss = jnp.where(valid, lse - pick_acc[:][:, :1], 0.0)
        loss_ref[:] = jnp.broadcast_to(loss, (br, _STAT_LANES))
        lse_ref[:] = jnp.broadcast_to(lse, (br, _STAT_LANES))


def _xent_bwd_kernel(x_ref, lbl_ref, lse_ref, g_ref, dx_ref, *, block_v):
    x = x_ref[:].astype(jnp.float32)                   # (block_rows, bv)
    lbl = lbl_ref[:][:, :1]
    lse = lse_ref[:][:, :1]
    g = g_ref[:][:, :1]
    p = jnp.exp(x - lse)
    col = (jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
           + pl.program_id(1) * block_v)
    valid = (lbl >= 0).astype(jnp.float32)
    dx = jnp.where(col == lbl, p - 1.0, p) * (g * valid)
    dx_ref[:] = dx.astype(dx_ref.dtype)


@jax.custom_vjp
def _fused_xent_2d(logits, labels):
    return _fused_xent_2d_fwd(logits, labels)[0]


@_x32
def _fused_xent_2d_fwd(logits, labels):
    rows, v = logits.shape
    br, bv, rows_pad, v_pad = _xent_blocks(rows, v)
    # pad vocab with -inf so padded columns vanish from the logsumexp
    xp = _pad_dim(_pad_dim(logits, 0, rows_pad), 1, v_pad,
                  value=_NEG_INF)
    lp = _lanes(_pad_dim(labels.astype(jnp.int32), 0, rows_pad, value=-1))
    with _kernel_span("softmax_cross_entropy", "fwd"):
        loss, lse = pl.pallas_call(
        functools.partial(_xent_fwd_kernel, block_v=bv),
        grid=(rows_pad // br, v_pad // bv),
        in_specs=[
            pl.BlockSpec((br, bv), lambda i, j: (i, j)),
            pl.BlockSpec((br, _STAT_LANES), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((br, _STAT_LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((br, _STAT_LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows_pad, _STAT_LANES), jnp.float32),
            jax.ShapeDtypeStruct((rows_pad, _STAT_LANES), jnp.float32),
        ],
        scratch_shapes=stat_scratch(br, 3),
        interpret=_interpret(),
    )(xp, lp)
    return loss[:rows, 0], (logits, labels, lse[:rows])


@_x32
def _fused_xent_2d_bwd(res, g):
    logits, labels, lse = res
    rows, v = logits.shape
    br, bv, rows_pad, v_pad = _xent_blocks(rows, v)
    xp = _pad_dim(_pad_dim(logits, 0, rows_pad), 1, v_pad,
                  value=_NEG_INF)
    lp = _lanes(_pad_dim(labels.astype(jnp.int32), 0, rows_pad, value=-1))
    lsep = _pad_dim(lse, 0, rows_pad)
    gp = _lanes(_pad_dim(g.astype(jnp.float32), 0, rows_pad))
    with _kernel_span("softmax_cross_entropy", "bwd"):
        dx = pl.pallas_call(
        functools.partial(_xent_bwd_kernel, block_v=bv),
        grid=(rows_pad // br, v_pad // bv),
        in_specs=[
            pl.BlockSpec((br, bv), lambda i, j: (i, j)),
            pl.BlockSpec((br, _STAT_LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((br, _STAT_LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((br, _STAT_LANES), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((br, bv), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((rows_pad, v_pad), logits.dtype),
        interpret=_interpret(),
    )(xp, lp, lsep, gp)
    return dx[:rows, :v], None


_fused_xent_2d.defvjp(_fused_xent_2d_fwd, _fused_xent_2d_bwd)


def fused_softmax_cross_entropy(logits, labels):
    """Per-example softmax cross-entropy from integer labels.

    logits: [..., V]; labels: [...] int. Labels < 0 are ignored (loss 0,
    zero gradient), matching softmax_with_cross_entropy ignore_index
    handling after relabeling.
    """
    logits, = _demote_f64(logits)
    shape = logits.shape
    v = shape[-1]
    loss = _fused_xent_2d(logits.reshape(-1, v), labels.reshape(-1))
    return loss.reshape(shape[:-1])


# =====================================================================
# Paged decode attention (serving)
# =====================================================================

def _paged_attn_kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
                       acc_ref, m_ref, l_ref, *, block_size, scale,
                       w_last):
    """One (batch, head, table-slot) program over a paged KV pool.

    Scalar-prefetched block tables drive the K/V BlockSpec index maps,
    so each program streams exactly the block its sequence owns at slot
    ``w`` — the online-softmax state (acc/m/l) lives in VMEM scratch
    and survives the sequential innermost grid dim.  The single query
    row is broadcast to 8 sublanes to satisfy Mosaic's (8, 128) tiling;
    row 0 is written out at the last slot.
    """
    b = pl.program_id(0)
    w = pl.program_id(2)
    ctx = cl_ref[b]

    @pl.when(w == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(w * block_size < ctx)
    def _block():
        d = q_ref.shape[-1]
        q = jnp.broadcast_to(q_ref[0, 0].astype(jnp.float32),
                             (_STAT_LANES, d))          # (8, D)
        k = k_ref[0, 0].astype(jnp.float32)             # (bs, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (8, bs)
        col = (jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
               + w * block_size)
        mask = col < ctx
        s = jnp.where(mask, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # explicit zero on masked cols (exp(_NEG_INF - m) is 1 when a
        # block were fully masked; the pl.when guard makes that
        # unreachable but keep the invariant local)
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = _lanes(alpha * l_ref[:, :1]
                            + jnp.sum(p, axis=-1, keepdims=True))
        v = v_ref[0, 0].astype(jnp.float32)             # (bs, D)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = _lanes(m_new)

    @pl.when(w == w_last)
    def _emit():
        l = l_ref[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        out = acc_ref[...] / l_safe
        # ctx==0 pad row -> zeros.  Broadcast the f32 stat and compare
        # at full shape, never broadcast the (rows, 1) predicate: the
        # Mosaic lowering of a bool broadcast_in_dim expands i1 through
        # an integer select/compare whose width follows the x64 mode AT
        # LOWERING TIME (outside the _x32 scope), and the layout pass
        # aborts on i64 ("bitwidth_ <= 32").
        out = jnp.where(jnp.broadcast_to(l, out.shape) > 0.0, out, 0.0)
        o_ref[...] = out[:1][None, None].astype(o_ref.dtype)


@_x32
def paged_attention(q, k_pool, v_pool, block_tables, context_lens,
                    scale=None):
    """Decode attention through per-sequence block tables.

    q: [B, 1, H, D]; k_pool/v_pool: [num_blocks, H, block_size, D];
    block_tables: [B, W] int32 pool block ids (pad entries -> block 0);
    context_lens: [B] int32 visible tokens per sequence (0 -> zero
    output, matching the XLA fallback's any_visible semantics).
    Returns [B, 1, H, D].
    """
    q, k_pool, v_pool = _demote_f64(q, k_pool, v_pool)
    B, s, H, D = q.shape
    if s != 1:
        raise ValueError(f"paged_attention decodes 1 token, got s={s}")
    num_blocks, _, block_size, _ = k_pool.shape
    W = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    qt = jnp.swapaxes(q, 1, 2)                          # [B, H, 1, D]
    bt = block_tables.astype(jnp.int32)
    cl = context_lens.astype(jnp.int32)

    with _kernel_span("paged_attention", "fwd"):
        out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, block_size=block_size,
                          scale=float(scale), w_last=W - 1),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, W),
            in_specs=[
                pl.BlockSpec((1, 1, 1, D),
                             lambda b, h, w, bt, cl: (b, h, 0, 0)),
                pl.BlockSpec((1, 1, block_size, D),
                             lambda b, h, w, bt, cl: (bt[b, w], h, 0, 0)),
                pl.BlockSpec((1, 1, block_size, D),
                             lambda b, h, w, bt, cl: (bt[b, w], h, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, 1, D),
                                   lambda b, h, w, bt, cl: (b, h, 0, 0)),
            scratch_shapes=softmax_scratch(_STAT_LANES, D),
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=_interpret(),
    )(bt, cl, qt, k_pool, v_pool)
    return jnp.swapaxes(out, 1, 2)                      # [B, 1, H, D]
