"""Elementwise & scalar math ops (paddle.tensor.math parity).

Reference parity: `python/paddle/tensor/math.py` → phi elementwise kernels
[UNVERIFIED — empty reference mount].  Pure jnp impls; XLA fuses chains of
these into single kernels, replacing phi's hand-fused variants.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.dtypes import to_jax_dtype
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "float_power", "maximum", "minimum", "fmax", "fmin",
    "sqrt", "rsqrt", "square", "exp", "expm1", "log", "log2", "log10",
    "log1p", "abs", "neg", "sign", "sin", "cos", "tan", "asin", "acos",
    "atan", "atan2", "sinh", "cosh", "tanh", "asinh", "acosh", "atanh",
    "floor", "ceil", "round", "trunc", "frac", "clip", "reciprocal", "erf",
    "erfinv", "lerp", "addmm", "isnan", "isinf", "isfinite", "nan_to_num",
    "logsumexp", "logit", "lgamma", "digamma", "multiply_", "add_",
    "subtract_", "scale", "stanh", "rad2deg", "deg2rad", "heaviside",
    "hypot", "ldexp", "logaddexp", "inner", "outer", "kron", "trace",
    "polar", "frexp", "nextafter",
    "deg2rad", "diff", "angle", "conj", "real", "imag", "gcd", "lcm",
    "cumsum", "cumprod", "cummax", "cummin", "sgn", "take", "increment",
    "copysign", "trapezoid", "cumulative_trapezoid", "logcumsumexp", "renorm", "gammaln", "polygamma", "i0", "i1", "sinc", "signbit", "isposinf", "isneginf", "isreal",
    "is_complex", "is_floating_point", "broadcast_shape", "histogramdd",
]


# Binary/unary elementwise bindings are GENERATED from ops.yaml
# (python -m paddle_tpu.ops.gen) — the reference's yaml->api.cc codegen
# role.  Only ops with bespoke signatures stay hand-written below.
from ._generated import (  # noqa: F401
    add, subtract, multiply, divide, floor_divide, mod, maximum, minimum,
    fmax, fmin, atan2, heaviside, hypot, logaddexp, ldexp, gcd, lcm, pow)

remainder = mod


float_power = pow

from ._generated import (  # noqa: F401
    sqrt, rsqrt, square, exp, expm1, log, log2, log10, log1p, abs, neg,
    sin, cos, tan, asin, acos, atan, sinh, cosh, tanh, asinh, acosh,
    atanh, floor, ceil, round, trunc, reciprocal, erf, erfinv, lgamma,
    digamma, rad2deg, deg2rad, angle, conj, real, imag, frac, sign)


sgn = sign


from ._generated import cumsum, cumprod, logsumexp  # noqa: F401
from ._generated import (  # noqa: F401  (sig-kind rows)
    addmm,
    clip,
    copysign,
    frexp,
    gammaln,
    i0,
    i1,
    inner,
    isfinite,
    isinf,
    isnan,
    isneginf,
    isposinf,
    isreal,
    kron,
    lerp,
    logcumsumexp,
    logit,
    nan_to_num,
    nextafter,
    outer,
    polar,
    polygamma,
    scale,
    signbit,
    sinc,
    stanh,
    take,
    trace,
    trapezoid,
)


def increment(x, value=1.0, name=None):
    y = dispatch("increment", lambda v, *, value: v + value, (x,),
                 dict(value=value))
    x._inplace_update(y._value, y._grad_node, y._out_index)
    return x


def _cum_extreme_impl(combine):
    """values via associative scan; indices = LAST position achieving
    the running extreme (torch/paddle tie convention), as the requested
    (paddle: `dtype`) integer type."""
    def impl(v, *, axis, idt):
        if axis is None:
            vf = v.reshape(-1)
            ax = 0
        else:
            vf, ax = v, axis
        vals = jax.lax.associative_scan(combine, vf, axis=ax)
        n = vf.shape[ax]
        ar = jnp.arange(n)
        shp = [1] * vf.ndim
        shp[ax] = n
        ar = ar.reshape(shp)
        hit = (vf == vals)
        idx = jax.lax.associative_scan(
            jnp.maximum, jnp.where(hit, ar, -1), axis=ax)
        return vals, idx.astype(idt)

    return impl


def cummax(x, axis=None, dtype="int64", name=None):
    return dispatch("cummax", _cum_extreme_impl(jnp.maximum), (x,),
                    dict(axis=None if axis is None else int(axis),
                         idt=to_jax_dtype(dtype)))


def cummin(x, axis=None, dtype="int64", name=None):
    return dispatch("cummin", _cum_extreme_impl(jnp.minimum), (x,),
                    dict(axis=None if axis is None else int(axis),
                         idt=to_jax_dtype(dtype)))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [x]
    if prepend is not None:
        args.append(prepend)
    if append is not None:
        args.append(append)

    def impl(v, *rest, n, axis, has_pre, has_app):
        pre = rest[0] if has_pre else None
        app = rest[1] if has_pre and has_app else (
            rest[0] if has_app else None)
        return jnp.diff(v, n=n, axis=axis, prepend=pre, append=app)

    return dispatch("diff", impl, tuple(args),
                    dict(n=int(n), axis=int(axis),
                         has_pre=prepend is not None,
                         has_app=append is not None))


# in-place variants
def add_(x, y, name=None):
    out = add(x, y)
    x._inplace_update(out._value, out._grad_node, out._out_index)
    return x


def subtract_(x, y, name=None):
    out = subtract(x, y)
    x._inplace_update(out._value, out._grad_node, out._out_index)
    return x


def multiply_(x, y, name=None):
    out = multiply(x, y)
    x._inplace_update(out._value, out._grad_node, out._out_index)
    return x


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def impl(yv, *maybe_x, dx, axis):
        import jax.scipy.integrate as _ji  # noqa: F401  (availability)
        xv = maybe_x[0] if maybe_x else None
        n = yv.shape[axis]
        y1 = jax.lax.slice_in_dim(yv, 1, n, axis=axis)
        y0 = jax.lax.slice_in_dim(yv, 0, n - 1, axis=axis)
        if xv is not None:
            x1 = jax.lax.slice_in_dim(xv, 1, n, axis=axis)
            x0 = jax.lax.slice_in_dim(xv, 0, n - 1, axis=axis)
            seg = (x1 - x0) * (y0 + y1) / 2.0
        else:
            seg = (1.0 if dx is None else dx) * (y0 + y1) / 2.0
        return jnp.cumsum(seg, axis=axis)
    args = (y, x) if x is not None else (y,)
    return dispatch("cumulative_trapezoid", impl, args,
                    dict(dx=dx, axis=axis))


def renorm(x, p, axis, max_norm, name=None):
    def impl(v, p, axis, max_norm):
        dims = [d for d in range(v.ndim) if d != axis]
        norms = jnp.sum(jnp.abs(v) ** p, axis=dims,
                        keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm,
                           max_norm / jnp.maximum(norms, 1e-12), 1.0)
        return v * factor
    return dispatch("renorm", impl, (x,),
                    dict(p=float(p), axis=int(axis),
                         max_norm=float(max_norm)))


def is_complex(x):
    return jnp.issubdtype(
        (x._value if isinstance(x, Tensor) else jnp.asarray(x)).dtype,
        jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(
        (x._value if isinstance(x, Tensor) else jnp.asarray(x)).dtype,
        jnp.floating)


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    import numpy as _np
    from ..core.tensor import to_tensor
    sample = _np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    w = None if weights is None else _np.asarray(
        weights.numpy() if isinstance(weights, Tensor) else weights)
    hist, edges = _np.histogramdd(sample, bins=bins, range=ranges,
                                  density=density, weights=w)
    return to_tensor(hist), [to_tensor(e) for e in edges]


