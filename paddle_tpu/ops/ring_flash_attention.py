"""Ring flash attention: the Pallas flash kernel blockwise over a ring.

Role of PaddleNLP's `ring_flash_attention` (per-rank KV rotation via
P2P, blockwise softmax accumulation [UNVERIFIED — empty reference
mount; SURVEY.md §2.3 SEP/CP row, §5 long-context]).

TPU-native: each device keeps its Q shard; K/V shards rotate around the
ICI ring with `jax.lax.ppermute`.  Every resident block is processed by
the SAME Mosaic flash-attention kernels used for local attention
(ops/pallas_kernels.py) — MXU-tiled, online-softmax — and the per-block
(out, lse) pairs are combined exactly via logsumexp reweighting.  The
backward is the true ring flash backward: the dq/dkv Pallas kernels run
per resident block against the GLOBAL lse/delta, dk/dv partials rotate
along with their K/V block, and one final ppermute delivers them home.

Causal structure on the ring (P shards, this device = `me`, ring step
r holds the block of device `src = (me - r) mod P`):
  r == 0           → the diagonal block: ordinary causal attention;
  1 <= r <= me     → a fully visible block (causal=False);
  r > me           → fully masked: contributes nothing (lax.cond skips
                     the kernel and yields -inf lse / zero grads).
Non-causal rings use the full flavor at every step.

Call `ring_flash_attention_local` inside shard_map (layout [B, S_local,
H, D]); `paddle_tpu.distributed...context_parallel.ring_attention`
routes here when the Pallas gate is open, with the jnp blockwise
implementation as the fallback.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .pallas_kernels import (_NEG_INF, _STAT_LANES, _flash_bwd,
                             _flash_fwd, _pad_dim, _pick_block,
                             _round_up, _demote_f64)

__all__ = ["ring_flash_attention_local"]


def _combine(out_run, lse_run, out_r, lse_r):
    """Merge a new normalized block result via logsumexp reweighting.

    lse arrays are in the (BH, S_pad, _STAT_LANES) stat-lane layout;
    `_NEG_INF` marks rows/blocks with no visible keys."""
    lse_new = jnp.logaddexp(lse_run, lse_r)
    dead_run = lse_run <= _NEG_INF / 2
    dead_r = lse_r <= _NEG_INF / 2
    w_run = jnp.where(dead_run, 0.0, jnp.exp(lse_run - lse_new))[..., :1]
    w_r = jnp.where(dead_r, 0.0, jnp.exp(lse_r - lse_new))[..., :1]
    out_new = (out_run.astype(jnp.float32) * w_run
               + out_r.astype(jnp.float32) * w_r)
    # rows dead in BOTH stay dead (lse ~ 2*_NEG_INF after logaddexp)
    lse_new = jnp.where(dead_run & dead_r, _NEG_INF, lse_new)
    return out_new, lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash_bhsd(q, k, v, scale, causal, axis, axis_size):
    out, _ = _ring_flash_bhsd_fwd(q, k, v, scale, causal, axis,
                                  axis_size)
    return out


def _ring_flash_bhsd_fwd(q, k, v, scale, causal, axis, axis_size):
    bh, s, d = q.shape
    bq = _pick_block(s, 0)
    bk = _pick_block(s, 1)
    s_pad = _round_up(s, bq)
    qp = _pad_dim(q, 1, s_pad)
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    k_cur, v_cur = k, v
    out_run = jnp.zeros((bh, s_pad, d), jnp.float32)
    lse_run = jnp.full((bh, s_pad, _STAT_LANES), _NEG_INF, jnp.float32)

    for r in range(axis_size):
        kp = _pad_dim(k_cur, 1, _round_up(s, bk))
        vp = _pad_dim(v_cur, 1, _round_up(s, bk))

        def _block(kp=kp, vp=vp, diag=(r == 0)):
            return _flash_fwd(qp, kp, vp, scale, causal and diag,
                              s, s, bq, bk)

        if causal and r > 0:
            o_r, lse_r = jax.lax.cond(
                me >= r, lambda: _block(),
                lambda: (jnp.zeros((bh, s_pad, d), q.dtype),
                         jnp.full((bh, s_pad, _STAT_LANES), _NEG_INF,
                                  jnp.float32)))
        else:
            o_r, lse_r = _block()
        out_run, lse_run = _combine(out_run, lse_run, o_r, lse_r)
        if r != axis_size - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)

    out = out_run.astype(q.dtype)
    return out[:, :s], (q, k, v, out, lse_run)


def _ring_flash_bhsd_bwd(scale, causal, axis, axis_size, res, g):
    q, k, v, out_pad, lse_tot = res
    bh, s, d = q.shape
    bq = _pick_block(s, 0)
    bk = _pick_block(s, 1)
    s_pad = _round_up(s, bq)
    qp = _pad_dim(q, 1, s_pad)
    gp = _pad_dim(g, 1, s_pad)
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    k_cur, v_cur = k, v
    dq = jnp.zeros((bh, s, d), jnp.float32)
    dk_cur = jnp.zeros((bh, s, d), jnp.float32)
    dv_cur = jnp.zeros((bh, s, d), jnp.float32)

    for r in range(axis_size):
        kp = _pad_dim(k_cur, 1, _round_up(s, bk))
        vp = _pad_dim(v_cur, 1, _round_up(s, bk))

        def _block(kp=kp, vp=vp, diag=(r == 0)):
            # global out/lse → _flash_bwd's internal delta and p are the
            # GLOBAL softmax restricted to this block: the exact ring
            # flash backward decomposition
            dq_p, dk_p, dv_p = _flash_bwd(
                qp, kp, vp, gp, out_pad, lse_tot, scale,
                causal and diag, s, s, bq, bk)
            return dq_p[:, :s], dk_p[:, :s], dv_p[:, :s]

        if causal and r > 0:
            dq_r, dk_r, dv_r = jax.lax.cond(
                me >= r, lambda: _block(),
                lambda: (jnp.zeros((bh, s, d), q.dtype),
                         jnp.zeros((bh, s, d), k.dtype),
                         jnp.zeros((bh, s, d), v.dtype)))
        else:
            dq_r, dk_r, dv_r = _block()
        dq = dq + dq_r.astype(jnp.float32)
        dk_cur = dk_cur + dk_r.astype(jnp.float32)
        dv_cur = dv_cur + dv_r.astype(jnp.float32)
        if r != axis_size - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
            dk_cur = jax.lax.ppermute(dk_cur, axis, perm)
            dv_cur = jax.lax.ppermute(dv_cur, axis, perm)

    # dk_cur on device i now holds the full grads of block (i+1) mod P;
    # one more hop delivers every block's grads to its owner
    dk_home = jax.lax.ppermute(dk_cur, axis, perm)
    dv_home = jax.lax.ppermute(dv_cur, axis, perm)
    return (dq.astype(q.dtype), dk_home.astype(k.dtype),
            dv_home.astype(v.dtype))


_ring_flash_bhsd.defvjp(_ring_flash_bhsd_fwd, _ring_flash_bhsd_bwd)


def ring_flash_attention_local(q, k, v, *, axis, axis_size,
                               causal=False, scale=None):
    """Pallas ring flash attention; call inside shard_map.

    q/k/v: local shards [B, S_local, H, D]; returns [B, S_local, H, D].
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    q, k, v = _demote_f64(q, k, v)
    b, s, h, d = q.shape
    qt = jnp.swapaxes(q, 1, 2).reshape(b * h, s, d)
    kt = jnp.swapaxes(k, 1, 2).reshape(b * h, s, d)
    vt = jnp.swapaxes(v, 1, 2).reshape(b * h, s, d)
    out = _ring_flash_bhsd(qt, kt, vt, float(scale), bool(causal),
                           axis, int(axis_size))
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2).astype(q.dtype)
