"""Op library + Tensor method attachment.

Reference parity: the generated eager methods (`paddle/fluid/pybind/
eager_method.cc` + generated `_C_ops` [UNVERIFIED — empty reference mount]).
Where Paddle code-generates C++ pybind methods from ops.yaml, we attach the
pure-Python op functions onto Tensor here (ops/ops.yaml documents the
catalog).
"""
from __future__ import annotations

from . import creation, math, manipulation, linalg, reduction, comparison
from ..core.tensor import Tensor

_METHODS = {}


def _collect(mod, names=None):
    for n in (names or mod.__all__):
        if hasattr(mod, n):
            _METHODS[n] = getattr(mod, n)


_collect(math)
_collect(manipulation)
_collect(linalg)
_collect(reduction)
_collect(comparison)
_collect(creation, ["zeros_like", "ones_like", "full_like", "tril", "triu",
                    "clone", "uniform_", "normal_", "exponential_"])

# names that clash with python builtins but must exist as methods
_SKIP_AS_METHOD = {"is_tensor", "to_tensor", "getitem", "setitem"}

for _name, _fn in _METHODS.items():
    if _name in _SKIP_AS_METHOD:
        continue
    if not hasattr(Tensor, _name):
        setattr(Tensor, _name, _fn)

# ---- operator dunders ----

def _swap(fn):
    def op(self, other):
        return fn(other if isinstance(other, Tensor)
                  else creation.to_tensor(other), self)
    return op


Tensor.__add__ = math.add
Tensor.__radd__ = lambda self, o: math.add(self, o)
Tensor.__sub__ = math.subtract
Tensor.__rsub__ = _swap(math.subtract)
Tensor.__mul__ = math.multiply
Tensor.__rmul__ = lambda self, o: math.multiply(self, o)
Tensor.__truediv__ = math.divide
Tensor.__rtruediv__ = _swap(math.divide)
Tensor.__floordiv__ = math.floor_divide
Tensor.__rfloordiv__ = _swap(math.floor_divide)
Tensor.__mod__ = math.mod
Tensor.__rmod__ = _swap(math.mod)
Tensor.__pow__ = math.pow
Tensor.__rpow__ = _swap(math.pow)
Tensor.__matmul__ = linalg.matmul
Tensor.__rmatmul__ = _swap(linalg.matmul)
Tensor.__neg__ = math.neg
Tensor.__abs__ = math.abs
Tensor.__invert__ = comparison.logical_not
Tensor.__and__ = comparison.bitwise_and
Tensor.__or__ = comparison.bitwise_or
Tensor.__xor__ = comparison.bitwise_xor
Tensor.__lshift__ = comparison.bitwise_left_shift
Tensor.__rshift__ = comparison.bitwise_right_shift
Tensor.__eq__ = comparison.equal
Tensor.__ne__ = comparison.not_equal
Tensor.__lt__ = comparison.less_than
Tensor.__le__ = comparison.less_equal
Tensor.__gt__ = comparison.greater_than
Tensor.__ge__ = comparison.greater_equal
Tensor.__hash__ = lambda self: id(self)

Tensor.mean = reduction.mean
Tensor.dot = linalg.dot
Tensor.matmul = linalg.matmul
Tensor.mm = linalg.mm
Tensor.norm = linalg.norm
Tensor.dim = lambda self: self.ndim


# ---- method-only fills (reference eager_method.cc surface) ----

def _fill_(self, value):
    """In-place fill with a scalar."""
    import jax.numpy as jnp
    self._inplace_update(jnp.full_like(self._value, value))
    return self


def _zero_(self):
    import jax.numpy as jnp
    self._inplace_update(jnp.zeros_like(self._value))
    return self


def _clip_(self, min=None, max=None):
    out = math.clip(self, min, max)
    self._inplace_update(out._value, out._grad_node, out._out_index)
    return self


def _scale_(self, scale=1.0, bias=0.0, bias_after_scale=True):
    out = math.scale(self, scale, bias, bias_after_scale)
    self._inplace_update(out._value, out._grad_node, out._out_index)
    return self


def _lerp_(self, y, weight):
    out = math.lerp(self, y, weight)
    self._inplace_update(out._value, out._grad_node, out._out_index)
    return self


def _sigmoid(self, name=None):
    from ..nn.functional.activation import sigmoid as _f
    return _f(self)


def _softmax(self, axis=-1, name=None):
    from ..nn.functional.activation import softmax as _f
    return _f(self, axis=axis)


Tensor.fill_ = _fill_
Tensor.zero_ = _zero_
Tensor.clip_ = _clip_
Tensor.scale_ = _scale_
Tensor.lerp_ = _lerp_
Tensor.sigmoid = _sigmoid
Tensor.softmax = _softmax
Tensor.ndimension = lambda self: self.ndim
if not hasattr(Tensor, "nonzero"):
    Tensor.nonzero = manipulation.nonzero
