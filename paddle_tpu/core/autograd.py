"""Eager autograd: a Paddle-semantics tape over JAX VJPs.

Reference parity: the eager engine (`paddle/fluid/eager/` — GradNodeBase,
backward.cc topo-queue executor [UNVERIFIED paths; reference mount empty]).

TPU-native design (SURVEY.md §7): each traced op records a ``GradNode`` whose
``vjp_fn`` comes from ``jax.vjp`` of the op's pure-JAX implementation.
``Tensor.backward()`` walks the recorded graph in reverse creation order and
materializes gradients into ``param.grad`` — Paddle's imperative semantics on
a functional core.  Because every vjp_fn is a pure JAX callable, the whole
tape (forward + backward + optimizer) is re-traceable under ``jax.jit``:
``paddle.jit.to_static`` compiles exactly this same code path.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax.numpy as jnp

from .lazy import concrete as _lazy_concrete, lazy_add

__all__ = [
    "GradNode", "backward", "grad", "no_grad", "enable_grad",
    "set_grad_enabled", "is_grad_enabled",
]


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — gradients of outputs w.r.t. inputs, not touching .grad.

    Implemented by running the tape walker with accumulation redirected
    into a side dict keyed by the requested input tensors.
    """
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]

    keep = bool(retain_graph) or bool(create_graph)
    stops = []
    if no_grad_vars:
        for t in no_grad_vars:
            stops.append((t, t.stop_gradient))
            t.stop_gradient = True
    # temporarily make requested inputs grad-eligible leaves
    for t in inputs:
        stops.append((t, t.stop_gradient))
        t.stop_gradient = False
    sink: dict = {}
    removers = []
    for t in inputs:
        if t._grad_node is not None:
            # non-leaf input: capture its cotangent via a backward hook
            def make_hook(tt):
                def hook(g):
                    _sink_accumulate(sink, tt, g._value)
                    return None
                return hook
            removers.append(t.register_hook(make_hook(t)))
    try:
        backward(outputs, grad_outputs, retain_graph=keep, grad_sink=sink)
        results = []
        for t in inputs:
            g = sink.get(id(t))
            if g is None:
                if not allow_unused:
                    from ..ops.creation import zeros_like
                    results.append(zeros_like(t))
                else:
                    results.append(None)
            else:
                results.append(Tensor(g, _internal=True,
                                      stop_gradient=True))
        return results
    finally:
        for r in removers:
            r.remove()
        for t, sg in stops:
            t.stop_gradient = sg


_node_counter = itertools.count()


class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_grad_state = _GradState()


def is_grad_enabled() -> bool:
    return _grad_state.enabled


@contextlib.contextmanager
def set_grad_enabled(mode: bool):
    prev = _grad_state.enabled
    _grad_state.enabled = bool(mode)
    try:
        yield
    finally:
        _grad_state.enabled = prev


class no_grad:
    """paddle.no_grad — usable as context manager or decorator."""

    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = False
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad(no_grad):
    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = True
        return self


class GradNode:
    """One recorded op in the autograd graph.

    ``vjp_fn(cotangents_tuple) -> tuple(input_cotangents)`` — straight from
    ``jax.vjp``.  ``inputs`` holds the input Tensors (keeps upstream graph
    alive); per-input ``needs_grad`` masks stop_gradient inputs.
    """

    __slots__ = (
        "id", "name", "vjp_fn", "inputs", "needs_grad", "n_outputs",
        "out_shapes_dtypes",
    )

    def __init__(self, name, vjp_fn, inputs, needs_grad, n_outputs,
                 out_shapes_dtypes):
        self.id = next(_node_counter)
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)
        self.needs_grad = list(needs_grad)
        self.n_outputs = n_outputs
        self.out_shapes_dtypes = out_shapes_dtypes

    def release(self):
        self.vjp_fn = None
        self.inputs = []

    def __repr__(self):
        return f"GradNode<{self.name}#{self.id}>"


def _sink_accumulate(sink, t, g):
    k = id(t)
    sink[k] = g if k not in sink else lazy_add(sink[k], g)


def _accumulate(t, g):
    """Accumulate cotangent ``g`` (a raw jax array) into tensor ``t``'s .grad.

    Reads/writes go through the trace-aware accessors so that gradient
    accumulation across steps is captured as state by to_static.
    """
    from .tensor import Tensor

    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True, _internal=True)
        t.grad.name = (t.name or "tensor") + "@GRAD"
    else:
        t.grad._inplace_update(lazy_add(t.grad.value(), g))


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             grad_sink: Optional[dict] = None):
    """Run reverse-mode from ``tensors`` (list of roots).

    Paddle semantics: leaf tensors with stop_gradient=False receive ``.grad``
    (accumulated across calls); non-leaf grads are not retained.
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # pending cotangents: node.id -> [cotangent-or-None per output]
    pending: dict[int, list] = {}
    nodes: dict[int, GradNode] = {}

    def seed(node, idx, cot):
        lst = pending.setdefault(node.id, [None] * node.n_outputs)
        lst[idx] = cot if lst[idx] is None else lazy_add(lst[idx], cot)
        nodes[node.id] = node

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            continue
        if g is None:
            if t._value.size != 1:
                raise ValueError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {tuple(t._value.shape)}"
                )
            v = t._value
            gv = jnp.ones(getattr(v, "shape", ()), v.dtype)
        else:
            gv = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._grad_node
        if node is None:
            if grad_sink is not None:
                _sink_accumulate(grad_sink, t, gv)
            else:
                _accumulate(t, gv)
        else:
            seed(node, t._out_index, gv)

    # Reverse-topological by creation id: a node's inputs were always created
    # before the node, so descending id order is a valid reverse topo order.
    import heapq

    heap = [-nid for nid in nodes]
    heapq.heapify(heap)
    inheap = set(nodes)
    visited = []
    while heap:
        nid = -heapq.heappop(heap)
        inheap.discard(nid)
        node = nodes[nid]
        cots = pending.pop(nid)
        if node.vjp_fn is None:
            raise RuntimeError(
                f"Trying to backward through the graph a second time "
                f"(node {node.name}); set retain_graph=True."
            )
        # fill missing output cotangents with zeros
        full = tuple(
            c if c is not None else jnp.zeros(s, d)
            for c, (s, d) in zip(cots, node.out_shapes_dtypes)
        )
        if not getattr(node.vjp_fn, "_lazy_ok", False):
            # jitted/plain vjp closures reject LazyValue arguments
            full = tuple(_lazy_concrete(c) for c in full)
        if node.n_outputs == 1:
            in_cots = node.vjp_fn(full[0])
        else:
            in_cots = node.vjp_fn(full)
        visited.append(node)
        for t, ng, ic in zip(node.inputs, node.needs_grad, in_cots):
            if not ng or ic is None:
                continue
            if t._backward_hooks:
                from .tensor import Tensor as _T

                for h in list(t._backward_hooks):
                    res = h(_T(ic, _internal=True, stop_gradient=True))
                    if res is not None:
                        ic = res._value if isinstance(res, _T) else ic
            child = t._grad_node
            if child is None:
                if not t.stop_gradient:
                    if grad_sink is not None:
                        _sink_accumulate(grad_sink, t, ic)
                    else:
                        _accumulate(t, ic)
            else:
                lst = pending.setdefault(child.id, [None] * child.n_outputs)
                i = t._out_index
                lst[i] = ic if lst[i] is None else lazy_add(lst[i], ic)
                if child.id not in nodes:
                    nodes[child.id] = child
                if child.id not in inheap and child.id in pending:
                    heapq.heappush(heap, -child.id)
                    inheap.add(child.id)

    if not retain_graph:
        for node in visited:
            node.release()
