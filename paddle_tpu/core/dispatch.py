"""Op dispatch: the KernelFactory equivalent, TPU-native.

Reference parity: Paddle routes every op through generated ``*_ad_func`` →
phi KernelFactory (backend, layout, dtype) → kernel (`paddle/phi/core/
kernel_factory.h`, `paddle/fluid/eager/` [UNVERIFIED — empty reference
mount]).  Here there is exactly ONE backend — XLA — so "kernel selection"
collapses: every op has a pure-JAX ``impl(*arrays, **attrs)``; dispatch
decides only (a) eager vs static-graph capture and (b) whether to record a
GradNode via ``jax.vjp``.

AMP hook: like the generated AMP branch in Paddle's dygraph functions, the
amp module installs a caster that rewrites input dtypes per op white/black
lists before the impl runs.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from . import autograd
from .dtypes import to_paddle_dtype

__all__ = ["dispatch", "OpDef", "OP_REGISTRY", "register_op"]


class OpDef:
    __slots__ = ("name", "impl", "n_outputs", "differentiable")

    def __init__(self, name, impl, n_outputs=1, differentiable=True):
        self.name = name
        self.impl = impl
        self.n_outputs = n_outputs
        self.differentiable = differentiable


OP_REGISTRY: dict[str, OpDef] = {}


def register_op(name, impl, n_outputs=1, differentiable=True):
    op = OpDef(name, impl, n_outputs, differentiable)
    OP_REGISTRY[name] = op
    return op


class _DispatchState(threading.local):
    def __init__(self):
        # static-graph capture hook: fn(name, impl, args, attrs) -> outputs
        self.static_hook = None
        # AMP caster: fn(name, tensor_args) -> tensor_args
        self.amp_caster = None


_state = _DispatchState()


def get_dispatch_state():
    return _state


def _is_float(v) -> bool:
    return jnp.issubdtype(v.dtype, jnp.floating) or jnp.issubdtype(
        v.dtype, jnp.complexfloating
    )


def dispatch(name: str, impl: Callable, args: Sequence[Any], attrs=None,
             differentiable: bool = True):
    """Run op ``name``.

    ``args`` may mix Tensors and raw python values (scalars keep JAX weak-type
    promotion).  Returns Tensor or tuple of Tensors mirroring impl's output.
    """
    from .tensor import Tensor

    attrs = attrs or {}

    if _state.static_hook is not None:
        return _state.static_hook(name, impl, args, attrs)

    if _state.amp_caster is not None:
        args = _state.amp_caster(name, args)

    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensors = [args[i] for i in tensor_idx]
    arrays = [t.value() for t in tensors]

    needs = [
        (not t.stop_gradient) and _is_float(v)
        for t, v in zip(tensors, arrays)
    ]
    record = (
        differentiable
        and autograd.is_grad_enabled()
        and any(needs)
    )

    if not record:
        full = list(args)
        for i, v in zip(tensor_idx, arrays):
            full[i] = v
        outs = impl(*full, **attrs)
        return _wrap(outs, name, node=None)

    def fn(*arrs):
        full = list(args)
        for i, v in zip(tensor_idx, arrs):
            full[i] = v
        return impl(*full, **attrs)

    outs, vjp_fn = jax.vjp(fn, *arrays)
    is_multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if is_multi else (outs,)
    node = autograd.GradNode(
        name,
        vjp_fn,
        tensors,
        needs,
        len(outs_t),
        [(o.shape, o.dtype) for o in outs_t],
    )
    return _wrap(outs, name, node=node)


def _wrap(outs, name, node):
    from .tensor import Tensor

    is_multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if is_multi else (outs,)
    wrapped = []
    for i, o in enumerate(outs_t):
        if o is None:
            wrapped.append(None)
            continue
        t = Tensor(o, stop_gradient=(node is None), _internal=True)
        if node is not None:
            t._grad_node = node
            t._out_index = i
        wrapped.append(t)
    if is_multi:
        return tuple(wrapped)
    return wrapped[0]
