"""Op dispatch: the KernelFactory equivalent, TPU-native.

Reference parity: Paddle routes every op through generated ``*_ad_func`` →
phi KernelFactory (backend, layout, dtype) → kernel (`paddle/phi/core/
kernel_factory.h`, `paddle/fluid/eager/` [UNVERIFIED — empty reference
mount]).  Here there is exactly ONE backend — XLA — so "kernel selection"
collapses: every op has a pure-JAX ``impl(*arrays, **attrs)``; dispatch
decides only (a) eager vs static-graph capture and (b) whether to record a
GradNode via ``jax.vjp``.

AMP hook: like the generated AMP branch in Paddle's dygraph functions, the
amp module installs a caster that rewrites input dtypes per op white/black
lists before the impl runs.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from . import autograd
from . import lazy as _lazy
from .dtypes import to_paddle_dtype
from ..observability.timeline import enabled as _obs_enabled

__all__ = ["dispatch", "OpDef", "OP_REGISTRY", "register_op"]


class OpDef:
    __slots__ = ("name", "impl", "n_outputs", "differentiable")

    def __init__(self, name, impl, n_outputs=1, differentiable=True):
        self.name = name
        self.impl = impl
        self.n_outputs = n_outputs
        self.differentiable = differentiable


OP_REGISTRY: dict[str, OpDef] = {}


def register_op(name, impl, n_outputs=1, differentiable=True):
    op = OpDef(name, impl, n_outputs, differentiable)
    OP_REGISTRY[name] = op
    return op


class _DispatchState(threading.local):
    def __init__(self):
        # static-graph capture hook: fn(name, impl, args, attrs) -> outputs
        self.static_hook = None
        # AMP caster: fn(name, tensor_args) -> tensor_args
        self.amp_caster = None


_state = _DispatchState()

# ---------------------------------------------------------------------
# Eager per-op executable cache (SURVEY.md §3.1: per-op dispatch is THE
# dygraph bottleneck).  Instead of tracing jax.vjp anew and executing
# the op primitive-by-primitive on every eager call, each (op, impl
# code, static args, input avals) signature gets ONE jitted
# forward(+vjp) executable; jax.vjp's returned function is a pytree
# (residual arrays + static structure), so it crosses the jit boundary
# and a single shared jitted applier runs the backward.  Ops whose impl
# closes over free variables, or with unhashable statics, fall back to
# the plain eager path (the cache must key all behavior).
# ---------------------------------------------------------------------
_EAGER_JIT_MAX = 4096
# Bounded LRUs: a long-running dynamic workload must keep caching its
# CURRENT working set.  The old insert-stop policy froze the cache at
# the first _EAGER_JIT_MAX signatures — every later op silently lost
# caching forever (re-traced per call).  Hits refresh recency; inserts
# past the cap evict the least-recently-dispatched signature and count
# into stats/`eager.cache_evictions`.
_eager_fwd_cache: OrderedDict = OrderedDict()
_eager_vjp_cache: OrderedDict = OrderedDict()
cache_evictions = {"fwd": 0, "vjp": 0}
_bwd_apply = None


def _cache_get(cache, key):
    v = cache.get(key)
    if v is not None:
        cache.move_to_end(key)
    return v


def _cache_put(cache, key, val, lane):
    cache[key] = val
    if len(cache) > _EAGER_JIT_MAX:
        cache.popitem(last=False)
        cache_evictions[lane] += 1
        if _obs_enabled():
            from ..observability.registry import get_registry
            get_registry().counter("eager.cache_evictions").inc()

# dtype -> str(dtype) memo: numpy dtype __str__ allocates on every call
# and _jit_key stringifies every operand's dtype on every eager dispatch
# — at trace-cache-hit steady state that was a measurable slice of the
# 1000x eager overhead (lenet_dygraph triage).
_DTYPE_STR: dict = {}


def _dtype_str(dt):
    s = _DTYPE_STR.get(dt)
    if s is None:
        s = _DTYPE_STR[dt] = str(dt)
    return s


# Live per-op cache-fragmentation watch at the insert sites: an op
# accumulating many jitted variants is quietly recompiling instead of
# hitting its cache.  Crossing the threshold records the TPU202/TPU203
# classification from analysis.audit_eager_cache once per op.
_FRAG_THRESHOLD = int(os.environ.get(
    "PADDLE_TPU_EAGER_FRAG_THRESHOLD", "16"))
_frag_counts: dict = {}
_frag_flagged: set = set()


def _note_cache_insert(name):
    n = _frag_counts.get(name, 0) + 1
    _frag_counts[name] = n
    if n != _FRAG_THRESHOLD or name in _frag_flagged:
        return
    _frag_flagged.add(name)
    from ..analysis.diagnostics import record
    from ..analysis.recompile import audit_eager_cache
    merged = {**_eager_fwd_cache, **_eager_vjp_cache}
    for d in audit_eager_cache(cache=merged, per_op_threshold=1):
        if d.site == f"eager:{name}":
            record(d)


def _get_bwd_apply():
    global _bwd_apply
    if _bwd_apply is None:
        _bwd_apply = jax.jit(lambda vjp_fn, cts: vjp_fn(cts))
    return _bwd_apply


_HASHABLE = (bool, int, float, str, bytes, type(None), slice,
             type(Ellipsis))


def _static_sig(v):
    import numpy as _np
    if isinstance(v, slice):
        return ("slice", v.start, v.stop, v.step)
    if isinstance(v, _HASHABLE):
        # type tag: 2, 2.0 and True hash/compare equal but trace to
        # different graphs (dtype promotion)
        return (type(v).__name__, v)
    if isinstance(v, _np.generic):
        return (type(v).__name__, v.item())
    if isinstance(v, _np.dtype):
        # dtype-valued attrs (cast's target dtype): without this, cast
        # had no cache key at all — every AMP cast re-traced per call
        # and, under the lazy tier, forced a segment flush
        return ("dtype", v.str)
    if isinstance(v, type) and issubclass(v, _np.generic):
        return ("dtype", v.__name__)
    if isinstance(v, (tuple, list)):
        return tuple(_static_sig(x) for x in v)
    raise TypeError


def _cell_sig(v, _depth=0):
    """Hashable signature for one closure cell; TypeError when the cell
    holds anything whose behavior the key could not capture."""
    if callable(v) and hasattr(v, "__code__"):
        cells = v.__closure__ or ()
        if _depth > 3:
            raise TypeError
        return ("fn", v.__code__, tuple(
            _cell_sig(c.cell_contents, _depth + 1) for c in cells))
    return _static_sig(v)


def _jit_key(name, impl, args, tensor_idx, arrays, attrs):
    from ..framework.flags import get_flags
    if not get_flags("FLAGS_eager_op_jit")["FLAGS_eager_op_jit"]:
        return None
    code = getattr(impl, "__code__", None)
    if code is None:
        # builtins / jnp ufuncs: no closure to worry about; key on the
        # (hashable) callable itself
        try:
            hash(impl)
        except TypeError:
            return None
        code = impl
    elif code.co_freevars:
        # closures over hashable config (conv dimension specs etc.) are
        # cacheable: the cell values ride in the key.  Function-valued
        # cells (the _rng_op wrapper around dropout impls) key by their
        # code objects.  Anything else (tensors, mutable state) keeps
        # the op out of the caches.  Empty cells raise ValueError.
        try:
            free = tuple(_cell_sig(c.cell_contents)
                         for c in impl.__closure__)
        except (TypeError, ValueError):
            return None
        code = (code, free)
    tset = set(tensor_idx)
    try:
        statics = tuple(
            (i, _static_sig(a)) for i, a in enumerate(args)
            if i not in tset)
        attr_sig = tuple(sorted(
            (k, _static_sig(v)) for k, v in attrs.items()))
    except TypeError:
        return None
    aval_sig = tuple((v.shape, _dtype_str(v.dtype)) for v in arrays)
    return (name, code, statics, attr_sig, aval_sig)


def get_dispatch_state():
    return _state


def _is_float(v) -> bool:
    return jnp.issubdtype(v.dtype, jnp.floating) or jnp.issubdtype(
        v.dtype, jnp.complexfloating
    )


def dispatch(name: str, impl: Callable, args: Sequence[Any], attrs=None,
             differentiable: bool = True):
    """Run op ``name``.

    ``args`` may mix Tensors and raw python values (scalars keep JAX weak-type
    promotion).  Returns Tensor or tuple of Tensors mirroring impl's output.

    With observability on, eager dispatches feed the
    ``eager.dispatch_us`` histogram (host-side overhead per op — the
    metric behind the lenet_dygraph 1000x triage); off, the timing
    costs one global read.
    """
    if _obs_enabled() and _state.static_hook is None:
        t0 = time.perf_counter()
        try:
            return _dispatch(name, impl, args, attrs, differentiable)
        finally:
            from ..observability.registry import get_registry
            get_registry().histogram("eager.dispatch_us").observe(
                (time.perf_counter() - t0) * 1e6)
    return _dispatch(name, impl, args, attrs, differentiable)


def _dispatch(name: str, impl: Callable, args: Sequence[Any], attrs,
              differentiable: bool):
    from .tensor import Tensor

    attrs = attrs or {}

    # AMP runs BEFORE the static hook: auto_cast inside program_guard
    # must record cast ops into the Program (the reference's static AMP
    # pass role).  Variables are Tensors with aval _values, so the
    # caster's dtype checks work symbolically.  Round-5 window-3 found
    # the opposite order silently building all-f32 "AMP" programs.
    if _state.amp_caster is not None:
        args = _state.amp_caster(name, args)

    if _state.static_hook is not None:
        return _state.static_hook(name, impl, args, attrs)

    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensors = [args[i] for i in tensor_idx]
    arrays = [t.value() for t in tensors]

    needs = [
        (not t.stop_gradient) and _is_float(v)
        for t, v in zip(tensors, arrays)
    ]
    record = (
        differentiable
        and autograd.is_grad_enabled()
        and any(needs)
    )

    key = _jit_key(name, impl, args, tensor_idx, arrays, attrs)

    # ---- lazy eager (SURVEY §7): record instead of dispatching ----
    if _lazy._EVER_ENABLED:  # keep the default hot path untouched
        if (key is not None and _lazy.lazy_enabled()
                and not any(isinstance(a, jax.core.Tracer)
                            for a in arrays)):
            out = _lazy_dispatch(name, impl, args, attrs, tensor_idx,
                                 tensors, arrays, needs, record, key)
            if out is not _LAZY_UNSUPPORTED:
                return out
        # fallback paths need concrete arrays (jax.vjp rejects LazyValue)
        arrays = [_lazy.concrete(a) for a in arrays]

    if not record:
        if key is not None:
            cached = _cache_get(_eager_fwd_cache, key)
            if cached is None:
                # None at tensor slots: the closure must not pin the
                # first call's Tensors (and their autograd graphs)
                template = [None if i in set(tensor_idx) else a
                            for i, a in enumerate(args)]

                def pure_fwd(*arrs, _t=template, _ti=tuple(tensor_idx),
                             _impl=impl, _attrs=attrs):
                    full = list(_t)
                    for i, v in zip(_ti, arrs):
                        full[i] = v
                    return _impl(*full, **_attrs)

                cached = jax.jit(pure_fwd)
                _cache_put(_eager_fwd_cache, key, cached, "fwd")
                _note_cache_insert(name)
            if cached is not None:
                return _wrap(cached(*arrays), name, node=None)
        full = list(args)
        for i, v in zip(tensor_idx, arrays):
            full[i] = v
        outs = impl(*full, **attrs)
        return _wrap(outs, name, node=None)

    def fn(*arrs):
        full = list(args)
        for i, v in zip(tensor_idx, arrs):
            full[i] = v
        return impl(*full, **attrs)

    if key is not None:
        cached = _cache_get(_eager_vjp_cache, key)
        if cached is None:
            template = [None if i in set(tensor_idx) else a
                        for i, a in enumerate(args)]

            def pure_pair(*arrs, _t=template, _ti=tuple(tensor_idx),
                          _impl=impl, _attrs=attrs):
                def f(*inner):
                    full = list(_t)
                    for i, v in zip(_ti, inner):
                        full[i] = v
                    return _impl(*full, **_attrs)
                return jax.vjp(f, *arrs)

            cached = jax.jit(pure_pair)
            _cache_put(_eager_vjp_cache, key, cached, "vjp")
            _note_cache_insert(name)
        if cached is not None:
            outs, raw_vjp = cached(*arrays)
            apply = _get_bwd_apply()

            def vjp_fn(cts, _raw=raw_vjp, _apply=apply):
                return _apply(_raw, cts)

            is_multi = isinstance(outs, (tuple, list))
            outs_t = tuple(outs) if is_multi else (outs,)
            node = autograd.GradNode(
                name, vjp_fn, tensors, needs, len(outs_t),
                [(o.shape, o.dtype) for o in outs_t])
            return _wrap(outs, name, node=node)

    outs, vjp_fn = jax.vjp(fn, *arrays)
    is_multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if is_multi else (outs,)
    node = autograd.GradNode(
        name,
        vjp_fn,
        tensors,
        needs,
        len(outs_t),
        [(o.shape, o.dtype) for o in outs_t],
    )
    return _wrap(outs, name, node=node)


_LAZY_UNSUPPORTED = object()


class _NoneOutputs(Exception):
    pass


# (name, code-sig) pairs whose python scalars must stay static: hoisting
# them to traced leaves made abstract eval fail (shape-/value-dependent
# scalars — axis args, output sizes).  Learned once, then permanent.
_NO_HOIST: set = set()


def _lazy_dispatch(name, impl, args, attrs, tensor_idx, tensors, arrays,
                   needs, record, key):
    """Record the op into the lazy segment buffer; no device dispatch.

    Bare python int/float positionals (scale factors, loop counters —
    ``x * lr_t``) are hoisted to weak-typed traced leaves so a changing
    scalar does NOT change the node key, and a training loop whose only
    per-step difference is a counter fingerprints to the SAME segment.
    Ops whose scalars are load-bearing for shapes fail the hoisted
    abstract eval once, land in _NO_HOIST, and keep them static.

    Returns _LAZY_UNSUPPORTED when the op cannot be abstractly
    evaluated at all (host-value-dependent impls) — caller falls
    through to the immediate path."""
    from . import lazy as _lazy

    name_, code, statics, attr_sig, aval_sig = key
    tset = set(tensor_idx)
    hoist = tuple(i for i, a in enumerate(args)
                  if i not in tset and type(a) in (int, float))
    if hoist and (name, code) not in _NO_HOIST:
        try:
            hvals = [jnp.asarray(args[i]) for i in hoist]
            hset = set(hoist)
            lkey = (name_, code,
                    tuple(s for s in statics if s[0] not in hset),
                    attr_sig,
                    aval_sig + tuple(
                        ((), _dtype_str(v.dtype), True) for v in hvals),
                    True)
            return _lazy_record(name, impl, args, attrs, tensor_idx,
                                tensors, arrays, needs, record, lkey,
                                hoist, hvals)
        except Exception:
            _NO_HOIST.add((name, code))
    try:
        return _lazy_record(name, impl, args, attrs, tensor_idx,
                            tensors, arrays, needs, record, key, (), [])
    except Exception:
        return _LAZY_UNSUPPORTED


def _lazy_record(name, impl, args, attrs, tensor_idx, tensors, arrays,
                 needs, record, lkey, hoist, hvals):
    from . import lazy as _lazy

    # ONE big-tuple hash per dispatch: the structural key is interned to
    # an int here; the abs_eval cache, the node key and the segment
    # fingerprint all ride on the int
    kid = _lazy._intern_key(lkey)
    tset = set(tensor_idx) | set(hoist)
    template = [None if i in tset else a for i, a in enumerate(args)]
    ext_idx = tuple(tensor_idx) + hoist
    ext_arrays = list(arrays) + hvals
    in_avals = [_lazy._aval_of(a) for a in ext_arrays]
    meta = _lazy.abs_eval(kid, record, template, ext_idx, attrs,
                          impl, in_avals, n_diff=len(tensor_idx))
    if record and any(meta["none_mask"]):
        raise _NoneOutputs(name)

    lazy_outs = _lazy.record_node(meta["run"], ext_arrays,
                                  meta["all_avals"],
                                  ("fwd", kid, record),
                                  label=name, raw_key=lkey)
    n_out = len(meta["out_avals"])
    outs = lazy_outs[:n_out]

    if not record:
        if meta["is_multi"]:
            full, it = [], iter(outs)
            for isnone in meta["none_mask"]:
                full.append(None if isnone else next(it))
            return _wrap(full, name, node=None)
        return _wrap(outs[0], name, node=None)

    res_vals = lazy_outs[n_out:]
    vjp_fn = _lazy.make_lazy_vjp(kid, res_vals, meta["treedef"],
                                 meta["out_struct"])
    node = autograd.GradNode(
        name, vjp_fn, tensors, needs, n_out,
        [(o.shape, o.dtype) for o in outs])
    return _wrap(tuple(outs) if meta["is_multi"] else outs[0], name,
                 node=node)


def _wrap(outs, name, node):
    from .tensor import Tensor

    is_multi = isinstance(outs, (tuple, list))
    outs_t = tuple(outs) if is_multi else (outs,)
    wrapped = []
    for i, o in enumerate(outs_t):
        if o is None:
            wrapped.append(None)
            continue
        t = Tensor(o, stop_gradient=(node is None), _internal=True)
        if node is not None:
            t._grad_node = node
            t._out_index = i
        wrapped.append(t)
    if is_multi:
        return tuple(wrapped)
    return wrapped[0]
