"""Lazy eager execution: the auto-trace tier for dygraph.

Reference parity: Paddle's dygraph hides per-op latency with generated
C++ paths and async CUDA launches (`paddle/fluid/eager/`,
SURVEY.md §3.1: per-op dispatch is THE dygraph bottleneck) [UNVERIFIED —
empty reference mount].  On TPU the equivalent lever is SURVEY.md §7's
"dygraph without per-op sync": eager ops build lazy expressions and
flush to ONE cached compiled segment at sync points — `.numpy()`,
`float()`, control flow on values, anything that truly needs data.

How a train step executes under lazy mode:
  * forward ops append ``LazyNode``s; outputs are ``LazyValue``s whose
    shape/dtype come from ``jax.eval_shape`` (InferMeta's role) — no
    device dispatch happens;
  * ops that need autograd record their VJP residuals as EXTRA lazy
    outputs (``jax.vjp``'s returned function is a pytree of residual
    arrays + static structure, captured abstractly at record time), so
    ``loss.backward()``'s tape walk records backward nodes into the SAME
    buffer — forward and backward become one graph;
  * the fused optimizer step consumes grads lazily too, so the whole
    train step — forward, backward, parameter update — flushes as ONE
    jitted, fingerprint-keyed segment at the first host read.  Steady
    state: 1–2 executable launches per step instead of hundreds of
    per-op round trips.

Fingerprinted reuse: a segment's structural fingerprint (interned
per-node op keys + wiring + leaf avals incl. weak-typedness + the
donation mask) keys a bounded LRU of AOT-compiled executables
(`TracedFunction`-style), so the second execution of a training-loop
body is a pure cache hit — zero retrace, zero relower.  Python scalars
are hoisted to weak-typed traced leaves by the dispatcher
(core/dispatch.py) so loop counters don't bake into the fingerprint.

Flush triggers: host reads (``__jax_array__``/``__array__``/``force``),
value-dependent control flow (``float()``/``bool()`` on a Tensor), and
the op-count watermark ``PADDLE_TPU_LAZY_MAX_NODES`` (re-read at every
``enable_lazy()``).

In-place param updates donate their old buffers: when a Tensor's buffer
is rebound to a pending LazyValue (optimizer ``p._inplace_update``),
the replaced concrete array is noted and — if nothing outside the
segment still references it at flush time — passed to XLA as a donated
argument, so params/opt-state cost 1x HBM in the replayed step (gated
on ``FLAGS_buffer_donation``; the donation mask is part of the
fingerprint).

Observability: each flush runs under a ``lazy:flush`` span
(``cat="dispatch"``, attrs: nodes, cache_hit, fingerprint), segment
compiles under ``compile:lazy:segment``; the metrics registry carries
``eager.segment_cache_hit_rate`` / ``eager.segment_cache_evictions``,
and ``phase_breakdown()`` exposes the lazy lane.  Fresh executables go
through the memory-guard preflight before their first dispatch, so
segments are held to the HBM budget like every other compiled program.

Enablement is PROCESS-global (``enable_lazy`` / ``PADDLE_TPU_LAZY=1`` /
``paddle.incubate.lazy_eager()``); each thread records into its own
buffer, and forcing a value flushes the buffer that owns it, so a
tensor produced on one thread may be read from another (checkpoint /
logging threads).
"""
from __future__ import annotations

import os
import threading
from collections import Counter, OrderedDict, deque

import numpy as np
import jax
import jax.numpy as jnp

from ..observability.timeline import (enabled as _obs_enabled,
                                      span as _span)

__all__ = ["LazyValue", "lazy_enabled", "enable_lazy", "lazy_guard",
           "flush", "concrete"]


class _Buffer:
    """One thread's pending segment."""

    __slots__ = ("pending", "flushing", "lock", "donate")

    def __init__(self):
        self.pending = []
        self.flushing = False
        self.lock = threading.RLock()
        # id(old array) -> old array for buffers an _inplace_update
        # replaced with a pending LazyValue (donation candidates); the
        # strong ref keeps the id stable until the flush decides
        self.donate = {}


class _ThreadState(threading.local):
    def __init__(self):
        self.buffer = _Buffer()


_tls = _ThreadState()

# process-global switch (fast path: a plain module attribute read)
_ENABLED = False
# sticky: once lazy has EVER been on, fallback paths must concretize
_EVER_ENABLED = False

# segment executable LRU: fingerprint key -> compiled AOT executable.
# Bounded like TracedFunction._cache; hits move to the back, inserts
# past the cap evict the least-recently-replayed segment.
_segment_cache: OrderedDict = OrderedDict()
_SEGMENT_CACHE_MAX = 512
# capture statistics (read by jit/sot.py reports and bench.py):
# monotonic counters
stats = {"flushes": 0, "cache_hits": 0, "compiles": 0, "nodes": 0,
         "evictions": 0, "donated": 0}
# per-op abstract-eval cache (also memoizes each op's replay `run`
# callable, so a steady-state dispatch allocates no new closures)
_abseval_cache: dict = {}
_ABSEVAL_CACHE_MAX = 8192


def _max_nodes_env(default=4096):
    try:
        return int(os.environ.get("PADDLE_TPU_LAZY_MAX_NODES", default))
    except (TypeError, ValueError):
        return default


# auto-flush watermark: a loop that never reads values must not grow
# the buffer without limit (PADDLE_TPU_LAZY_MAX_NODES, re-read at every
# enable_lazy so tests/jobs can retune without a restart)
_AUTO_FLUSH_NODES = _max_nodes_env()


def lazy_enabled():
    return _ENABLED and not _tls.buffer.flushing


def enable_lazy(on=True):
    """Switch lazy eager mode process-wide.  Returns previous mode."""
    global _ENABLED, _EVER_ENABLED, _AUTO_FLUSH_NODES
    prev = _ENABLED
    if prev and not on:
        flush()
    _ENABLED = bool(on)
    _EVER_ENABLED = _EVER_ENABLED or _ENABLED
    if on and "PADDLE_TPU_LAZY_MAX_NODES" in os.environ:
        # env knob re-read on every enable so jobs/tests can retune the
        # watermark without a process restart; a directly-assigned
        # module value (tests) is left alone when the env is unset
        _AUTO_FLUSH_NODES = _max_nodes_env(_AUTO_FLUSH_NODES)
    return prev


class lazy_guard:
    """Context manager: run a block in lazy eager mode."""

    def __init__(self, on=True):
        self.on = on

    def __enter__(self):
        self.prev = enable_lazy(self.on)
        return self

    def __exit__(self, *exc):
        enable_lazy(self.prev)
        return False


def _force_delegate(op):
    def fn(self, *args, **kwargs):
        return getattr(self.force(), op)(*args, **kwargs)
    fn.__name__ = op
    return fn


class LazyValue:
    """A deferred array: aval now, data after its segment flushes.

    Real data uses flush transparently: jnp/numpy conversion via
    ``__jax_array__``/``__array__``, unknown attributes (``.at``,
    ``.sharding``, ``.reshape`` …) via ``__getattr__``, and arithmetic
    dunders by force-and-delegate.  ``__add__`` alone stays lazy — it is
    the cotangent-accumulation path of the tape walk."""

    __slots__ = ("aval", "node", "out_index", "_concrete", "_error")

    def __init__(self, aval, node, out_index):
        self.aval = aval
        self.node = node
        self.out_index = out_index
        self._concrete = None
        self._error = None

    # ---- aval surface (keeps .shape/.dtype users working unforced) ----
    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    def force(self):
        if self._concrete is None:
            if self._error is not None:
                raise RuntimeError(
                    "this lazy value's segment failed to execute"
                ) from self._error
            self.node.buffer_flush()
            if self._concrete is None:
                if self._error is not None:
                    raise RuntimeError(
                        "this lazy value's segment failed to execute"
                    ) from self._error
                raise RuntimeError(
                    "lazy value did not materialize on flush")
        return self._concrete

    # jax/numpy interop: any real data use flushes transparently
    def __jax_array__(self):
        return self.force()

    def __array__(self, dtype=None):
        a = np.asarray(self.force())
        return a.astype(dtype) if dtype is not None else a

    def block_until_ready(self):
        self.force().block_until_ready()
        return self

    def __getattr__(self, name):
        # anything beyond the lazy surface (.at, .sharding, .devices,
        # .reshape, .astype …) forces and delegates to the real array
        if name.startswith("__"):
            raise AttributeError(name)
        return getattr(self.force(), name)

    def __add__(self, other):
        return lazy_add(self, other)

    def __radd__(self, other):
        return lazy_add(other, self)

    # force-and-delegate arithmetic for non-core consumers of ._value
    __sub__ = _force_delegate("__sub__")
    __rsub__ = _force_delegate("__rsub__")
    __mul__ = _force_delegate("__mul__")
    __rmul__ = _force_delegate("__rmul__")
    __truediv__ = _force_delegate("__truediv__")
    __rtruediv__ = _force_delegate("__rtruediv__")
    __pow__ = _force_delegate("__pow__")
    __neg__ = _force_delegate("__neg__")
    __matmul__ = _force_delegate("__matmul__")
    __getitem__ = _force_delegate("__getitem__")

    def __repr__(self):
        st = "pending" if self._concrete is None else "ready"
        return f"LazyValue({self.aval.shape}, {self.aval.dtype}, {st})"


class LazyNode:
    __slots__ = ("run", "inputs", "outs", "key", "buffer", "label",
                 "raw_key")

    def __init__(self, run, inputs, avals, key, buffer, label, raw_key):
        self.run = run                 # run(*input_vals) -> tuple
        self.inputs = list(inputs)     # LazyValue | concrete array
        self.key = key                 # interned int (fingerprint atom)
        self.buffer = buffer
        self.label = label             # op name, for TPU205 naming
        self.raw_key = raw_key         # structural key, for TPU205 diff
        self.outs = [LazyValue(a, self, i) for i, a in enumerate(avals)]

    def buffer_flush(self):
        buf = self.buffer
        if buf is not None:
            _flush_buffer(buf)


_aval_intern: dict = {}


def _aval_of(v):
    """ShapeDtypeStruct for one dispatch operand, interned by
    (shape, dtype, weak_type): the lazy recorder abstractifies every
    operand of every recorded op, and a training loop re-sees the same
    handful of signatures millions of times (the lenet eager-dispatch
    triage).  weak_type rides along because hoisted python scalars must
    keep python-number promotion inside the replayed program."""
    if isinstance(v, LazyValue):
        sig = (v.aval.shape, v.aval.dtype,
               bool(getattr(v.aval, "weak_type", False)))
    else:
        sig = (jnp.shape(v), jnp.result_type(v), _weak_of(v))
    aval = _aval_intern.get(sig)
    if aval is None:
        if len(_aval_intern) >= 4096:
            return jax.ShapeDtypeStruct(sig[0], sig[1], weak_type=sig[2])
        aval = _aval_intern[sig] = jax.ShapeDtypeStruct(
            sig[0], sig[1], weak_type=sig[2])
    return aval


def _weak_of(v):
    """Is ``v`` weakly typed for promotion purposes?  jax arrays carry
    the flag; bare python numbers ARE weak."""
    w = getattr(v, "weak_type", None)
    if w is not None:
        return bool(w)
    return isinstance(v, (bool, int, float, complex))


_key_intern: dict = {}
_intern_lock = threading.Lock()


def _intern_key(key):
    """Big structural op keys hash O(size) on every dict lookup; the
    segment wiring key contains one per node per flush, so nodes carry
    a small interned int instead.  Locked: a get-then-set race could
    hand one int to two different keys — wrong-replay territory."""
    i = _key_intern.get(key)
    if i is None:
        with _intern_lock:
            i = _key_intern.setdefault(key, len(_key_intern))
    return i


def record_node(run, inputs, out_avals, key, label="op", raw_key=None):
    """Append one node to this thread's buffer; returns its outputs.
    ``key`` may be pre-interned (int) or a structural tuple."""
    buf = _tls.buffer
    if len(buf.pending) >= _AUTO_FLUSH_NODES:
        # flush BEFORE appending: the new node's outputs have no Tensor
        # wrapper yet, so the liveness pruning would see them as dead
        _flush_buffer(buf)
    kid = key if isinstance(key, int) else _intern_key(key)
    node = LazyNode(run, inputs, out_avals, kid, buf, label,
                    raw_key if raw_key is not None else key)
    with buf.lock:  # another thread may be force-flushing this buffer
        buf.pending.append(node)
    return node.outs


def lazy_add(a, b):
    """Cotangent-accumulation add that stays lazy when either side is."""
    la, lb = isinstance(a, LazyValue), isinstance(b, LazyValue)
    if la and a._concrete is not None:
        a, la = a._concrete, False
    if lb and b._concrete is not None:
        b, lb = b._concrete, False
    if not (la or lb) or not lazy_enabled():
        a = a.force() if la else a
        b = b.force() if lb else b
        return a + b
    aa, ab = _aval_of(a), _aval_of(b)
    out = jax.eval_shape(jnp.add, aa, ab)
    key = ("lazy_add", aa.shape, str(aa.dtype), ab.shape, str(ab.dtype))
    return record_node(lambda x, y: (jnp.add(x, y),), [a, b],
                       [out], key, label="lazy_add")[0]


def note_donation(old, new):
    """Called by ``Tensor._inplace_update``: when a concrete buffer is
    replaced by a pending LazyValue (optimizer in-place param update),
    the old array becomes a donation candidate for this thread's next
    flush.  A forced LazyValue (last step's segment output — the steady
    state) donates its materialized array."""
    if not (isinstance(new, LazyValue) and new._concrete is None):
        return
    if isinstance(old, LazyValue):
        old = old._concrete
        if old is None:
            return
    if isinstance(old, jax.Array) and not isinstance(old,
                                                     jax.core.Tracer):
        _tls.buffer.donate[id(old)] = old


def concrete(v):
    """Force if lazy; identity otherwise."""
    return v.force() if isinstance(v, LazyValue) else v


def concrete_values(tensors):
    """``tuple(t._value, forced)`` — THE compiled-call boundary helper:
    a pending LazyValue handed to a lowered executable (or jit.lower)
    raises 'Triggering __jax_array__ during abstractification', so
    every site that feeds raw tensor buffers into compiled code goes
    through here."""
    return tuple(concrete(t._value) for t in tensors)


def flush():
    """Flush this thread's pending segment."""
    _flush_buffer(_tls.buffer)


def _flush_buffer(buf):
    with buf.lock:
        pending, buf.pending = buf.pending, []
        donate, buf.donate = buf.donate, {}
        if not pending:
            return
        buf.flushing = True
        try:
            _flush_nodes(pending, donate)
        except BaseException as e:
            # every in-flight value of this segment can never
            # materialize; remember the cause so later reads point at
            # the real error instead of a bare "did not materialize"
            for n in pending:
                for lv in n.outs:
                    if lv._concrete is None:
                        lv._error = e
            raise
        finally:
            buf.flushing = False


def _liveness_masks(pending):
    """Per-node tuple of bools: which outputs are referenced OUTSIDE the
    segment (a Tensor's ``_value``, a vjp closure's residual, another
    thread) and must therefore materialize.  Everything else stays
    INTERNAL to the replay program so XLA can fuse, DCE and reuse its
    buffers — returning every intermediate (activations, grads, adam
    temporaries) as a program output forbids all buffer reuse and was a
    10x+ step-time hit at GPT scale.

    Accounting: ``sys.getrefcount(lv)`` counts (1) the getrefcount arg,
    (2) the local binding, (3) the ``node.outs`` entry, plus one per
    in-segment consumer input — anything beyond that is external.
    Hidden references (objects kept alive in cycles, C-level containers)
    only OVERcount, i.e. materialize more than strictly needed — never
    the silent-drop direction; a genuinely-referenced value misjudged
    dead would fail LOUDLY at force() ("did not materialize")."""
    import sys
    # generator scope: no leaked local binding to skew the refcounts
    in_seg = Counter(id(v) for n in pending for v in n.inputs
                     if isinstance(v, LazyValue))
    masks = []
    for n in pending:
        m = []
        for i in range(len(n.outs)):
            lv = n.outs[i]
            ext = sys.getrefcount(lv) - 3 - in_seg.get(id(lv), 0)
            m.append(ext > 0)
            del lv
        masks.append(tuple(m))
    return masks


def _donatable_leaves(leaves, pending, donate):
    """Leaf indices safe to donate to XLA: the leaf was noted as an
    in-place-replaced buffer AND nothing outside this flush still
    references it.  Refcount accounting mirrors _liveness_masks: the
    expected count is getrefcount's own arg + the loop binding + the
    ``donate`` map's strong ref + every ``leaves``/``node.inputs``
    occurrence; anything beyond means a user still holds the old
    buffer — overcounting (hidden refs) only SKIPS a donation, never
    donates a live buffer."""
    if not donate:
        return ()
    from ..framework.flags import get_flags
    if not get_flags("FLAGS_buffer_donation")["FLAGS_buffer_donation"]:
        return ()
    import sys
    inputs_ct = Counter(id(v) for n in pending for v in n.inputs
                        if not isinstance(v, LazyValue))
    leaves_ct = Counter(id(v) for v in leaves)
    # a forced LazyValue input holds ONE ref to its materialized array
    # via _concrete.  That ref is creditable only when the LazyValue
    # itself has no references outside these input lists — a tensor
    # still bound to it (detach() alias, user variable) could read the
    # array after the flush, so it must block donation.
    lv_occ = Counter(id(v) for n in pending for v in n.inputs
                     if isinstance(v, LazyValue)
                     and v._concrete is not None)
    lv_credit = Counter()
    seen = set()
    for n in pending:
        for v in n.inputs:
            if not (isinstance(v, LazyValue)
                    and v._concrete is not None):
                continue
            vid = id(v)
            if vid in seen:
                continue
            seen.add(vid)
            # getrefcount arg + loop binding + input-list occurrences
            if sys.getrefcount(v) <= 2 + lv_occ[vid]:
                lv_credit[id(v._concrete)] += 1
    out = []
    for i in range(len(leaves)):
        v = leaves[i]
        vid = id(v)
        if vid not in donate or leaves_ct[vid] != 1:
            # aliased-operand duplicate slots can't donate one buffer
            # twice; keep it simple and keep them all
            del v
            continue
        expected = 3 + leaves_ct[vid] + inputs_ct[vid] + lv_credit[vid]
        if sys.getrefcount(v) <= expected:
            out.append(i)
        del v
    return tuple(out)


class _Segment:
    """One cached AOT-compiled segment executable."""

    __slots__ = ("compiled", "fingerprint", "n_donated")

    def __init__(self, compiled, fingerprint, n_donated):
        self.compiled = compiled
        self.fingerprint = fingerprint
        self.n_donated = n_donated


# segment compile history for the TPU205 thrash audit: every compiled
# fingerprint with its per-node structural keys, grouped by op-name
# sequence so the audit can diff two variants and NAME the node that
# keeps changing (a baked-in python scalar, a drifting shape)
_segment_history: deque = deque(maxlen=256)
_seg_groups: dict = {}          # label tuple -> set of fingerprints
_SEG_GROUPS_MAX = 512
_seg_flagged: set = set()


def _frag_threshold():
    try:
        return int(os.environ.get("PADDLE_TPU_EAGER_FRAG_THRESHOLD",
                                  "16"))
    except (TypeError, ValueError):
        return 16


def _note_segment_compile(fp, pending, leaf_sig):
    labels = tuple(n.label for n in pending)
    _segment_history.append({
        "fingerprint": fp,
        "labels": labels,
        "keys": tuple(n.raw_key for n in pending),
        "leaf_sig": leaf_sig,
    })
    if len(_seg_groups) < _SEG_GROUPS_MAX or labels in _seg_groups:
        group = _seg_groups.setdefault(labels, set())
        group.add(fp)
        if len(group) == _frag_threshold() \
                and labels not in _seg_flagged:
            # live thrash watch, same shape as dispatch._note_cache_insert
            _seg_flagged.add(labels)
            try:
                from ..analysis.diagnostics import record
                from ..analysis.recompile import audit_segment_cache
                for d in audit_segment_cache(only_labels=labels,
                                             threshold=1):
                    record(d)
            except Exception:
                pass


def _metrics_flush_update(hit):
    """Registry lanes (no-ops with observability off)."""
    from ..observability.registry import get_registry
    reg = get_registry()
    if hit:
        reg.counter("eager.segment_cache_hits").inc()
    else:
        reg.counter("eager.segment_cache_misses").inc()
    fl = stats["flushes"]
    if fl:
        reg.gauge("eager.segment_cache_hit_rate").set(
            stats["cache_hits"] / fl)


def _compile_segment(seg_key, pending, wiring, masks, leaves,
                     donate_idx, kept_idx, fp):
    runs = [n.run for n in pending]
    wires = [w for _, w in wiring]
    n_leaves = len(leaves)
    d_idx, k_idx = tuple(donate_idx), tuple(kept_idx)

    def replay(donated, kept):
        leaf_vals = [None] * n_leaves
        for i, v in zip(d_idx, donated):
            leaf_vals[i] = v
        for i, v in zip(k_idx, kept):
            leaf_vals[i] = v
        results = []
        out = []
        for run, slots, mask in zip(runs, wires, masks):
            ins = [results[s[1]][s[2]] if s[0] == "n"
                   else leaf_vals[s[1]] for s in slots]
            res = run(*ins)
            results.append(res)
            out.append(tuple(
                o for o, keep in zip(res, mask) if keep))
        return tuple(out)

    jit_kwargs = {}
    if d_idx:
        jit_kwargs["donate_argnums"] = (0,)
    donated = tuple(leaves[i] for i in d_idx)
    kept = tuple(leaves[i] for i in k_idx)
    with _span("compile:lazy:segment", cat="compile",
               nodes=len(pending), fingerprint=fp):
        compiled = jax.jit(replay, **jit_kwargs) \
            .lower(donated, kept).compile()
    # memory-guard preflight: hold the fresh segment executable to the
    # HBM budget (in-flight leaves + materialized outputs) before its
    # first dispatch, exactly like TracedFunction/Executor programs
    from ..memory.guard import preflight_check
    preflight_check(compiled, program=f"lazy:segment#{fp}")
    return _Segment(compiled, fp, len(d_idx))


def _flush_nodes(pending, donate=None):
    leaves = []
    leaf_pos: dict = {}          # id(array) -> leaf index
    wiring = []
    node_index = {id(n): i for i, n in enumerate(pending)}
    masks = _liveness_masks(pending)

    for n in pending:
        slots = []
        node_leaves = set()      # leaf indices already used by THIS node

        def leaf_slot(v):
            # share leaves ACROSS nodes, but aliased operands of one
            # node must stay distinct jit arguments: the recorded vjp
            # arity came from an abstract probe with per-occurrence
            # tracers, and jax dedupes jaxpr consts by identity — one
            # tracer in two operand slots drops residuals at replay
            k = leaf_pos.get(id(v))
            if k is None or k in node_leaves:
                new = len(leaves)
                leaves.append(v)
                if k is None:
                    leaf_pos[id(v)] = new
                k = new
            node_leaves.add(k)
            return ("l", k)

        for v in n.inputs:
            if isinstance(v, LazyValue) and v._concrete is not None:
                v = v._concrete
            if isinstance(v, LazyValue):
                ni = node_index.get(id(v.node))
                if ni is None:
                    # produced by another thread's (or a failed)
                    # segment: materialize it now
                    slots.append(leaf_slot(v.force()))
                    continue
                slots.append(("n", ni, v.out_index))
            else:
                slots.append(leaf_slot(v))
        wiring.append((n.key, tuple(slots)))

    donate_idx = _donatable_leaves(leaves, pending, donate)
    dset = set(donate_idx)
    kept_idx = tuple(i for i in range(len(leaves)) if i not in dset)
    leaf_sig = tuple(
        (jnp.shape(v), str(jnp.result_type(v)), _weak_of(v))
        for v in leaves)
    seg_key = (tuple(wiring), tuple(masks), leaf_sig, donate_idx)
    stats["flushes"] += 1
    stats["nodes"] += len(pending)
    seg = _segment_cache.get(seg_key)
    hit = seg is not None
    if hit:
        stats["cache_hits"] += 1
        _segment_cache.move_to_end(seg_key)
    else:
        stats["compiles"] += 1
        fp = _intern_key(seg_key)
        seg = _compile_segment(seg_key, pending, wiring, masks, leaves,
                               donate_idx, kept_idx, fp)
        _segment_cache[seg_key] = seg
        if len(_segment_cache) > _SEGMENT_CACHE_MAX:
            _segment_cache.popitem(last=False)
            stats["evictions"] += 1
            if _obs_enabled():
                from ..observability.registry import get_registry
                get_registry().counter(
                    "eager.segment_cache_evictions").inc()
        _note_segment_compile(fp, pending, leaf_sig)
    stats["donated"] += len(donate_idx)
    if _obs_enabled():
        _metrics_flush_update(hit)
    donated = tuple(leaves[i] for i in donate_idx)
    kept = tuple(leaves[i] for i in kept_idx)
    del leaves
    from ..device import hbm_oom_context
    with _span("lazy:flush", cat="dispatch", nodes=len(pending),
               cache_hit=hit, fingerprint=seg.fingerprint,
               donated=len(donated)):
        with hbm_oom_context():  # dygraph OOMs surface here
            out = seg.compiled(donated, kept)
    for n, vals, mask in zip(pending, out, masks):
        it = iter(vals)
        for lv, keep in zip(n.outs, mask):
            if keep:
                lv._concrete = next(it)
                # break the lv -> node -> sibling-outs chain: a rebound
                # tensor must free (and donate) last step's buffers, not
                # keep the whole flushed segment alive transitively
                lv.node = None
        n.run = None
        n.inputs = []
        n.buffer = None


# ---------------------------------------------------------------------
# dispatch integration (called from core.dispatch)
# ---------------------------------------------------------------------
def abs_eval(op_key, record, template, tensor_idx, attrs, impl,
             in_avals, n_diff=None):
    """Cached per-op abstract evaluation: output avals; for recorded ops
    also the VJP residual avals + pytree structure (captured via side
    effect during the abstract trace — the structure is static).

    The meta dict also memoizes the node's replay ``run`` callable:
    equal op keys prove behavioral equality (same contract as the
    per-op jit caches), so a steady-state dispatch reuses one closure
    instead of building template/closure objects per call.

    ``n_diff``: how many leading inputs are differentiable Tensor
    operands — hoisted python-scalar leaves ride after them and stay
    out of the VJP (their "gradient" is never consumed)."""
    cache_key = (op_key, bool(record))
    meta = _abseval_cache.get(cache_key)
    if meta is not None:
        return meta

    t_idx = tuple(tensor_idx)
    if n_diff is None:
        n_diff = len(t_idx)
    side = {}

    if not record:
        def probe(*ins):
            full = list(template)
            for i, v in zip(t_idx, ins):
                full[i] = v
            out = impl(*full, **attrs)
            side["is_multi"] = isinstance(out, (tuple, list))
            outs_t = tuple(out) if side["is_multi"] else (out,)
            side["none_mask"] = tuple(o is None for o in outs_t)
            return tuple(o for o in outs_t if o is not None)

        out_avals = jax.eval_shape(probe, *in_avals)
        meta = {"record": False, "out_avals": tuple(out_avals),
                "is_multi": side["is_multi"],
                "none_mask": side["none_mask"]}
    else:
        def probe(*ins):
            hoisted = ins[n_diff:]

            def f(*xs):
                full = list(template)
                for i, v in zip(t_idx, tuple(xs) + tuple(hoisted)):
                    full[i] = v
                return impl(*full, **attrs)

            outs, vjp = jax.vjp(f, *ins[:n_diff])
            res, treedef = jax.tree_util.tree_flatten(vjp)
            side["treedef"] = treedef
            side["is_multi"] = isinstance(outs, (tuple, list))
            side["out_struct"] = jax.tree_util.tree_structure(outs)
            outs_t = tuple(outs) if side["is_multi"] else (outs,)
            side["n_out"] = len(outs_t)
            side["none_mask"] = tuple(o is None for o in outs_t)
            return outs_t + tuple(res)

        all_avals = jax.eval_shape(probe, *in_avals)
        n_out = side["n_out"]
        meta = {"record": True,
                "out_avals": tuple(all_avals[:n_out]),
                "res_avals": tuple(all_avals[n_out:]),
                "treedef": side["treedef"],
                "out_struct": side["out_struct"],
                "is_multi": side["is_multi"],
                "none_mask": side["none_mask"]}
    meta["run"] = make_fwd_run(template, t_idx, attrs, impl, record,
                               n_diff)
    meta["all_avals"] = meta["out_avals"] + \
        tuple(meta.get("res_avals", ()))
    if len(_abseval_cache) < _ABSEVAL_CACHE_MAX:
        _abseval_cache[cache_key] = meta
    return meta


def make_fwd_run(template, tensor_idx, attrs, impl, record,
                 n_diff=None):
    """The node's replay function.  All behavior-affecting state is in
    the node key (op key), so identical keys may share compiled code."""
    t_idx = tuple(tensor_idx)
    if n_diff is None:
        n_diff = len(t_idx)
    if not record:
        def run(*ins):
            full = list(template)
            for i, v in zip(t_idx, ins):
                full[i] = v
            out = impl(*full, **attrs)
            outs_t = tuple(out) if isinstance(out, (tuple, list)) \
                else (out,)
            return tuple(o for o in outs_t if o is not None)
        return run

    def run(*ins):
        hoisted = ins[n_diff:]

        def f(*xs):
            full = list(template)
            for i, v in zip(t_idx, tuple(xs) + tuple(hoisted)):
                full[i] = v
            return impl(*full, **attrs)

        outs, vjp = jax.vjp(f, *ins[:n_diff])
        res, _ = jax.tree_util.tree_flatten(vjp)
        outs_t = tuple(outs) if isinstance(outs, (tuple, list)) \
            else (outs,)
        return outs_t + tuple(res)
    return run


def make_lazy_vjp(op_key, res_values, treedef, out_struct):
    """GradNode.vjp_fn for a lazily recorded op: applying it records a
    backward node into the (same) buffer, so backward defers too."""

    def vjp_fn(cts):
        flat_cts, _ = jax.tree_util.tree_flatten(
            cts, is_leaf=lambda x: isinstance(x, LazyValue))
        n_res = len(res_values)

        ct_sig = tuple((_aval_of(c).shape, str(_aval_of(c).dtype))
                       for c in flat_cts)
        key = ("bwd", op_key, ct_sig)
        meta = _abseval_cache.get(key)
        if meta is None:
            def bwd_run(*ins):
                vjp = jax.tree_util.tree_unflatten(treedef,
                                                   ins[:n_res])
                ct_vals = jax.tree_util.tree_unflatten(
                    out_struct, list(ins[n_res:]))
                return tuple(vjp(ct_vals))

            in_avals = [_aval_of(v) for v in res_values] + \
                [_aval_of(c) for c in flat_cts]
            meta = {"avals": tuple(jax.eval_shape(bwd_run, *in_avals)),
                    "run": bwd_run}
            if len(_abseval_cache) < _ABSEVAL_CACHE_MAX:
                _abseval_cache[key] = meta
        if lazy_enabled():
            return record_node(meta["run"],
                               list(res_values) + flat_cts,
                               list(meta["avals"]), key, label="bwd")
        vals = [concrete(v) for v in res_values] + \
            [concrete(c) for c in flat_cts]
        return meta["run"](*vals)

    vjp_fn._lazy_ok = True  # may receive LazyValue cotangents
    return vjp_fn
