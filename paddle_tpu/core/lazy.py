"""Lazy eager execution: defer op dispatch into a segment buffer.

Reference parity: Paddle's dygraph hides per-op latency with generated
C++ paths and async CUDA launches (`paddle/fluid/eager/`,
SURVEY.md §3.1: per-op dispatch is THE dygraph bottleneck) [UNVERIFIED —
empty reference mount].  On TPU the equivalent lever is SURVEY.md §7's
"dygraph without per-op sync": eager ops build lazy expressions and
flush to ONE cached compiled segment at sync points — `.numpy()`,
`float()`, control flow on values, anything that truly needs data.

How a train step executes under lazy mode:
  * forward ops append ``LazyNode``s; outputs are ``LazyValue``s whose
    shape/dtype come from ``jax.eval_shape`` (InferMeta's role) — no
    device dispatch happens;
  * ops that need autograd record their VJP residuals as EXTRA lazy
    outputs (``jax.vjp``'s returned function is a pytree of residual
    arrays + static structure, captured abstractly at record time), so
    ``loss.backward()``'s tape walk records backward nodes into the SAME
    buffer — forward and backward become one graph;
  * the fused optimizer step consumes grads through ``__jax_array__``,
    which forces the buffer: the whole forward+backward flushes as one
    jitted, cache-keyed segment, then the optimizer's own fused
    executable runs.  Steady state: ~2 executable launches per step
    instead of hundreds of per-op round trips.

A segment's jit cache key is the full structural wiring (per-node op
keys + which input is which earlier output vs leaf + leaf avals), so the
second iteration of a training loop replays a compiled executable.

Enablement is PROCESS-global (``enable_lazy`` / ``PADDLE_TPU_LAZY=1`` /
``paddle.incubate.lazy_eager()``); each thread records into its own
buffer, and forcing a value flushes the buffer that owns it, so a
tensor produced on one thread may be read from another (checkpoint /
logging threads).
"""
from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["LazyValue", "lazy_enabled", "enable_lazy", "lazy_guard",
           "flush", "concrete"]


class _Buffer:
    """One thread's pending segment."""

    __slots__ = ("pending", "flushing", "lock")

    def __init__(self):
        self.pending = []
        self.flushing = False
        self.lock = threading.RLock()


class _ThreadState(threading.local):
    def __init__(self):
        self.buffer = _Buffer()


_tls = _ThreadState()

# process-global switch (fast path: a plain module attribute read)
_ENABLED = False
# sticky: once lazy has EVER been on, fallback paths must concretize
_EVER_ENABLED = False

# segment executable cache: wiring key -> jitted replay fn
_segment_cache: dict = {}
_SEGMENT_CACHE_MAX = 512
# capture statistics (read by jit/sot.py reports): monotonic counters
stats = {"flushes": 0, "cache_hits": 0, "compiles": 0, "nodes": 0}
# per-op abstract-eval cache
_abseval_cache: dict = {}
_ABSEVAL_CACHE_MAX = 8192
# auto-flush bound: a loop that never reads values must not grow the
# buffer without limit
_AUTO_FLUSH_NODES = 4096


def lazy_enabled():
    return _ENABLED and not _tls.buffer.flushing


def enable_lazy(on=True):
    """Switch lazy eager mode process-wide.  Returns previous mode."""
    global _ENABLED, _EVER_ENABLED
    prev = _ENABLED
    if prev and not on:
        flush()
    _ENABLED = bool(on)
    _EVER_ENABLED = _EVER_ENABLED or _ENABLED
    return prev


class lazy_guard:
    """Context manager: run a block in lazy eager mode."""

    def __init__(self, on=True):
        self.on = on

    def __enter__(self):
        self.prev = enable_lazy(self.on)
        return self

    def __exit__(self, *exc):
        enable_lazy(self.prev)
        return False


def _force_delegate(op):
    def fn(self, *args, **kwargs):
        return getattr(self.force(), op)(*args, **kwargs)
    fn.__name__ = op
    return fn


class LazyValue:
    """A deferred array: aval now, data after its segment flushes.

    Real data uses flush transparently: jnp/numpy conversion via
    ``__jax_array__``/``__array__``, unknown attributes (``.at``,
    ``.sharding``, ``.reshape`` …) via ``__getattr__``, and arithmetic
    dunders by force-and-delegate.  ``__add__`` alone stays lazy — it is
    the cotangent-accumulation path of the tape walk."""

    __slots__ = ("aval", "node", "out_index", "_concrete", "_error")

    def __init__(self, aval, node, out_index):
        self.aval = aval
        self.node = node
        self.out_index = out_index
        self._concrete = None
        self._error = None

    # ---- aval surface (keeps .shape/.dtype users working unforced) ----
    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    def force(self):
        if self._concrete is None:
            if self._error is not None:
                raise RuntimeError(
                    "this lazy value's segment failed to execute"
                ) from self._error
            self.node.buffer_flush()
            if self._concrete is None:
                if self._error is not None:
                    raise RuntimeError(
                        "this lazy value's segment failed to execute"
                    ) from self._error
                raise RuntimeError(
                    "lazy value did not materialize on flush")
        return self._concrete

    # jax/numpy interop: any real data use flushes transparently
    def __jax_array__(self):
        return self.force()

    def __array__(self, dtype=None):
        a = np.asarray(self.force())
        return a.astype(dtype) if dtype is not None else a

    def block_until_ready(self):
        self.force().block_until_ready()
        return self

    def __getattr__(self, name):
        # anything beyond the lazy surface (.at, .sharding, .devices,
        # .reshape, .astype …) forces and delegates to the real array
        if name.startswith("__"):
            raise AttributeError(name)
        return getattr(self.force(), name)

    def __add__(self, other):
        return lazy_add(self, other)

    def __radd__(self, other):
        return lazy_add(other, self)

    # force-and-delegate arithmetic for non-core consumers of ._value
    __sub__ = _force_delegate("__sub__")
    __rsub__ = _force_delegate("__rsub__")
    __mul__ = _force_delegate("__mul__")
    __rmul__ = _force_delegate("__rmul__")
    __truediv__ = _force_delegate("__truediv__")
    __rtruediv__ = _force_delegate("__rtruediv__")
    __pow__ = _force_delegate("__pow__")
    __neg__ = _force_delegate("__neg__")
    __matmul__ = _force_delegate("__matmul__")
    __getitem__ = _force_delegate("__getitem__")

    def __repr__(self):
        st = "pending" if self._concrete is None else "ready"
        return f"LazyValue({self.aval.shape}, {self.aval.dtype}, {st})"


class LazyNode:
    __slots__ = ("run", "inputs", "outs", "key", "buffer")

    def __init__(self, run, inputs, avals, key, buffer):
        self.run = run                 # run(*input_vals) -> tuple
        self.inputs = list(inputs)     # LazyValue | concrete array
        self.key = key
        self.buffer = buffer
        self.outs = [LazyValue(a, self, i) for i, a in enumerate(avals)]

    def buffer_flush(self):
        buf = self.buffer
        if buf is not None:
            _flush_buffer(buf)


_aval_intern: dict = {}


def _aval_of(v):
    """ShapeDtypeStruct for one dispatch operand, interned by
    (shape, dtype): the lazy recorder abstractifies every operand of
    every recorded op, and a training loop re-sees the same handful of
    signatures millions of times (the lenet eager-dispatch triage)."""
    if isinstance(v, LazyValue):
        sig = (v.aval.shape, v.aval.dtype)
    else:
        sig = (jnp.shape(v), jnp.result_type(v))
    aval = _aval_intern.get(sig)
    if aval is None:
        if len(_aval_intern) >= 4096:
            return jax.ShapeDtypeStruct(*sig)
        aval = _aval_intern[sig] = jax.ShapeDtypeStruct(*sig)
    return aval


_key_intern: dict = {}
_intern_lock = threading.Lock()


def _intern_key(key):
    """Big structural op keys hash O(size) on every dict lookup; the
    segment wiring key contains one per node per flush, so nodes carry
    a small interned int instead.  Locked: a get-then-set race could
    hand one int to two different keys — wrong-replay territory."""
    i = _key_intern.get(key)
    if i is None:
        with _intern_lock:
            i = _key_intern.setdefault(key, len(_key_intern))
    return i


def record_node(run, inputs, out_avals, key):
    """Append one node to this thread's buffer; returns its outputs."""
    buf = _tls.buffer
    if len(buf.pending) >= _AUTO_FLUSH_NODES:
        # flush BEFORE appending: the new node's outputs have no Tensor
        # wrapper yet, so the liveness pruning would see them as dead
        _flush_buffer(buf)
    node = LazyNode(run, inputs, out_avals, _intern_key(key), buf)
    with buf.lock:  # another thread may be force-flushing this buffer
        buf.pending.append(node)
    return node.outs


def lazy_add(a, b):
    """Cotangent-accumulation add that stays lazy when either side is."""
    la, lb = isinstance(a, LazyValue), isinstance(b, LazyValue)
    if la and a._concrete is not None:
        a, la = a._concrete, False
    if lb and b._concrete is not None:
        b, lb = b._concrete, False
    if not (la or lb) or not lazy_enabled():
        a = a.force() if la else a
        b = b.force() if lb else b
        return a + b
    aa, ab = _aval_of(a), _aval_of(b)
    out = jax.eval_shape(jnp.add, aa, ab)
    key = ("lazy_add", aa.shape, str(aa.dtype), ab.shape, str(ab.dtype))
    return record_node(lambda x, y: (jnp.add(x, y),), [a, b],
                       [out], key)[0]


def concrete(v):
    """Force if lazy; identity otherwise."""
    return v.force() if isinstance(v, LazyValue) else v


def concrete_values(tensors):
    """``tuple(t._value, forced)`` — THE compiled-call boundary helper:
    a pending LazyValue handed to a lowered executable (or jit.lower)
    raises 'Triggering __jax_array__ during abstractification', so
    every site that feeds raw tensor buffers into compiled code goes
    through here."""
    return tuple(concrete(t._value) for t in tensors)


def flush():
    """Flush this thread's pending segment."""
    _flush_buffer(_tls.buffer)


def _flush_buffer(buf):
    with buf.lock:
        pending, buf.pending = buf.pending, []
        if not pending:
            return
        buf.flushing = True
        try:
            _flush_nodes(pending)
        except BaseException as e:
            # every in-flight value of this segment can never
            # materialize; remember the cause so later reads point at
            # the real error instead of a bare "did not materialize"
            for n in pending:
                for lv in n.outs:
                    if lv._concrete is None:
                        lv._error = e
            raise
        finally:
            buf.flushing = False


def _liveness_masks(pending):
    """Per-node tuple of bools: which outputs are referenced OUTSIDE the
    segment (a Tensor's ``_value``, a vjp closure's residual, another
    thread) and must therefore materialize.  Everything else stays
    INTERNAL to the replay program so XLA can fuse, DCE and reuse its
    buffers — returning every intermediate (activations, grads, adam
    temporaries) as a program output forbids all buffer reuse and was a
    10x+ step-time hit at GPT scale.

    Accounting: ``sys.getrefcount(lv)`` counts (1) the getrefcount arg,
    (2) the local binding, (3) the ``node.outs`` entry, plus one per
    in-segment consumer input — anything beyond that is external.
    Hidden references (objects kept alive in cycles, C-level containers)
    only OVERcount, i.e. materialize more than strictly needed — never
    the silent-drop direction; a genuinely-referenced value misjudged
    dead would fail LOUDLY at force() ("did not materialize")."""
    import sys
    from collections import Counter
    # generator scope: no leaked local binding to skew the refcounts
    in_seg = Counter(id(v) for n in pending for v in n.inputs
                     if isinstance(v, LazyValue))
    masks = []
    for n in pending:
        m = []
        for i in range(len(n.outs)):
            lv = n.outs[i]
            ext = sys.getrefcount(lv) - 3 - in_seg.get(id(lv), 0)
            m.append(ext > 0)
            del lv
        masks.append(tuple(m))
    return masks


def _flush_nodes(pending):
    leaves = []
    leaf_pos: dict = {}          # id(array) -> leaf index
    wiring = []
    node_index = {id(n): i for i, n in enumerate(pending)}
    masks = _liveness_masks(pending)

    for n in pending:
        slots = []
        node_leaves = set()      # leaf indices already used by THIS node

        def leaf_slot(v):
            # share leaves ACROSS nodes, but aliased operands of one
            # node must stay distinct jit arguments: the recorded vjp
            # arity came from an abstract probe with per-occurrence
            # tracers, and jax dedupes jaxpr consts by identity — one
            # tracer in two operand slots drops residuals at replay
            k = leaf_pos.get(id(v))
            if k is None or k in node_leaves:
                new = len(leaves)
                leaves.append(v)
                if k is None:
                    leaf_pos[id(v)] = new
                k = new
            node_leaves.add(k)
            return ("l", k)

        for v in n.inputs:
            if isinstance(v, LazyValue) and v._concrete is not None:
                v = v._concrete
            if isinstance(v, LazyValue):
                ni = node_index.get(id(v.node))
                if ni is None:
                    # produced by another thread's (or a failed)
                    # segment: materialize it now
                    slots.append(leaf_slot(v.force()))
                    continue
                slots.append(("n", ni, v.out_index))
            else:
                slots.append(leaf_slot(v))
        wiring.append((n.key, tuple(slots)))

    leaf_sig = tuple(
        (jnp.shape(v), str(jnp.result_type(v))) for v in leaves)
    seg_key = (tuple(wiring), tuple(masks), leaf_sig)
    stats["flushes"] += 1
    stats["nodes"] += len(pending)
    fn = _segment_cache.get(seg_key)
    if fn is not None:
        stats["cache_hits"] += 1
    if fn is None:
        stats["compiles"] += 1
        runs = [n.run for n in pending]
        wires = [w for _, w in wiring]

        def replay(leaf_vals):
            results = []
            kept = []
            for run, slots, mask in zip(runs, wires, masks):
                ins = [results[s[1]][s[2]] if s[0] == "n"
                       else leaf_vals[s[1]] for s in slots]
                out = run(*ins)
                results.append(out)
                kept.append(tuple(
                    o for o, keep in zip(out, mask) if keep))
            return tuple(kept)

        fn = jax.jit(replay)
        if len(_segment_cache) < _SEGMENT_CACHE_MAX:
            _segment_cache[seg_key] = fn
    from ..device import hbm_oom_context
    with hbm_oom_context():  # dygraph OOMs surface here
        out = fn(leaves)
    for n, vals, mask in zip(pending, out, masks):
        it = iter(vals)
        for lv, keep in zip(n.outs, mask):
            if keep:
                lv._concrete = next(it)
        n.run = None
        n.inputs = []
        n.buffer = None


# ---------------------------------------------------------------------
# dispatch integration (called from core.dispatch)
# ---------------------------------------------------------------------
def abs_eval(op_key, record, template, tensor_idx, attrs, impl,
             in_avals):
    """Cached per-op abstract evaluation: output avals; for recorded ops
    also the VJP residual avals + pytree structure (captured via side
    effect during the abstract trace — the structure is static)."""
    cache_key = (op_key, bool(record))
    meta = _abseval_cache.get(cache_key)
    if meta is not None:
        return meta

    t_idx = tuple(tensor_idx)
    side = {}

    if not record:
        def probe(*ins):
            full = list(template)
            for i, v in zip(t_idx, ins):
                full[i] = v
            out = impl(*full, **attrs)
            side["is_multi"] = isinstance(out, (tuple, list))
            outs_t = tuple(out) if side["is_multi"] else (out,)
            side["none_mask"] = tuple(o is None for o in outs_t)
            return tuple(o for o in outs_t if o is not None)

        out_avals = jax.eval_shape(probe, *in_avals)
        meta = {"record": False, "out_avals": tuple(out_avals),
                "is_multi": side["is_multi"],
                "none_mask": side["none_mask"]}
    else:
        def probe(*ins):
            def f(*xs):
                full = list(template)
                for i, v in zip(t_idx, xs):
                    full[i] = v
                return impl(*full, **attrs)

            outs, vjp = jax.vjp(f, *ins)
            res, treedef = jax.tree_util.tree_flatten(vjp)
            side["treedef"] = treedef
            side["is_multi"] = isinstance(outs, (tuple, list))
            side["out_struct"] = jax.tree_util.tree_structure(outs)
            side["n_out"] = (len(outs) if side["is_multi"] else 1)
            return (tuple(outs) if side["is_multi"] else (outs,)) \
                + tuple(res)

        all_avals = jax.eval_shape(probe, *in_avals)
        n_out = side["n_out"]
        meta = {"record": True,
                "out_avals": tuple(all_avals[:n_out]),
                "res_avals": tuple(all_avals[n_out:]),
                "treedef": side["treedef"],
                "out_struct": side["out_struct"],
                "is_multi": side["is_multi"],
                "none_mask": (False,) * n_out}
    if len(_abseval_cache) < _ABSEVAL_CACHE_MAX:
        _abseval_cache[cache_key] = meta
    return meta


def make_fwd_run(template, tensor_idx, attrs, impl, record):
    """The node's replay function.  All behavior-affecting state is in
    the node key (op key), so identical keys may share compiled code."""
    t_idx = tuple(tensor_idx)
    if not record:
        def run(*ins):
            full = list(template)
            for i, v in zip(t_idx, ins):
                full[i] = v
            out = impl(*full, **attrs)
            outs_t = tuple(out) if isinstance(out, (tuple, list)) \
                else (out,)
            return tuple(o for o in outs_t if o is not None)
        return run

    def run(*ins):
        def f(*xs):
            full = list(template)
            for i, v in zip(t_idx, xs):
                full[i] = v
            return impl(*full, **attrs)

        outs, vjp = jax.vjp(f, *ins)
        res, _ = jax.tree_util.tree_flatten(vjp)
        outs_t = tuple(outs) if isinstance(outs, (tuple, list)) \
            else (outs,)
        return outs_t + tuple(res)
    return run


def make_lazy_vjp(op_key, res_values, treedef, out_struct):
    """GradNode.vjp_fn for a lazily recorded op: applying it records a
    backward node into the (same) buffer, so backward defers too."""

    def vjp_fn(cts):
        flat_cts, _ = jax.tree_util.tree_flatten(
            cts, is_leaf=lambda x: isinstance(x, LazyValue))
        n_res = len(res_values)

        def bwd_run(*ins):
            vjp = jax.tree_util.tree_unflatten(treedef, ins[:n_res])
            ct_vals = jax.tree_util.tree_unflatten(
                out_struct, list(ins[n_res:]))
            return tuple(vjp(ct_vals))

        ct_sig = tuple((_aval_of(c).shape, str(_aval_of(c).dtype))
                       for c in flat_cts)
        key = ("bwd", op_key, ct_sig)
        meta = _abseval_cache.get(key)
        if meta is None:
            in_avals = [_aval_of(v) for v in res_values] + \
                [_aval_of(c) for c in flat_cts]
            meta = tuple(jax.eval_shape(bwd_run, *in_avals))
            if len(_abseval_cache) < _ABSEVAL_CACHE_MAX:
                _abseval_cache[key] = meta
        if lazy_enabled():
            return record_node(bwd_run, list(res_values) + flat_cts,
                               list(meta), key)
        vals = [concrete(v) for v in res_values] + \
            [concrete(c) for c in flat_cts]
        return bwd_run(*vals)

    vjp_fn._lazy_ok = True  # may receive LazyValue cotangents
    return vjp_fn
