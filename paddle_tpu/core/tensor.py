"""The eager Tensor: Paddle semantics over a jax.Array.

Reference parity: eager Tensor / DenseTensor (`paddle/phi/core/dense_tensor.h`,
`paddle/fluid/eager/` eager tensor wrapper, pybind `eager_method.cc`
[UNVERIFIED — empty reference mount]).

Design (SURVEY.md §7): a Tensor owns a ``jax.Array`` (device HBM buffer via
PJRT) plus autograd metadata (``stop_gradient``, ``grad``, ``_grad_node``).
Mutation (``set_value``, in-place ops, optimizer updates) swaps the underlying
buffer — under ``paddle.jit.to_static`` tracing these swaps are captured as
state outputs, which is how the imperative surface compiles to one pure XLA
program.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import autograd
from . import lazy as _lazy
from .dtypes import DType, convert_dtype, to_jax_dtype, to_paddle_dtype, default_dtype
from .place import CPUPlace, Place, TPUPlace, current_place

__all__ = ["Tensor", "to_tensor"]


class _TraceState(threading.local):
    def __init__(self):
        self.ctx = None  # set by paddle_tpu.jit tracing


_trace_state = _TraceState()


def get_trace_ctx():
    return _trace_state.ctx


def set_trace_ctx(ctx):
    _trace_state.ctx = ctx


_tensor_counter = [0]

# Serializes every "swap tensor._value for traced values, run, restore"
# region (jit/trace.py, the pipeline engines' pure sections): the trick
# temporarily puts tracers into LIVE layer objects, so a second thread
# touching the same layers mid-trace would read tracers.  All swap
# users must hold this lock for the whole swap-run-restore span.
value_swap_lock = threading.RLock()


import contextlib as _contextlib


@_contextlib.contextmanager
def swapped_values(swap, save_extra=(), save_grad=False):
    """THE swap-run-restore protocol, shared by every user of the
    tensor._value substitution trick (to_static tracing, the pipeline
    engines' pure sections, scan_layer_stack).

    ``swap``: iterable of (tensor, new_value) pairs substituted for the
    body.  ``save_extra``: additional tensors whose value/grad linkage
    must survive the body (mutation targets).  ``save_grad``: also
    snapshot/restore ``.grad``.  Everything happens under
    ``value_swap_lock`` with no pre-try window, so an exception anywhere
    restores state and releases the lock."""
    with value_swap_lock:
        swap = list(swap)
        tensors = [t for t, _ in swap] + list(save_extra)
        saved = [(t, t._value, t._grad_node,
                  t.grad if save_grad else None) for t in tensors]
        try:
            for t, v in swap:
                t._value = v
            yield
        finally:
            for t, v, gn, gr in saved:
                t._value = v
                t._grad_node = gn
                if save_grad:
                    t.grad = gr


class Tensor:
    __slots__ = (
        "_value", "stop_gradient", "grad", "_grad_node", "_out_index",
        "name", "persistable", "_backward_hooks", "is_leaf_param",
        "__weakref__", "__dict__",
    )

    def __init__(self, data, dtype=None, place=None, stop_gradient=True,
                 _internal=False):
        if _internal:
            self._value = data
        else:
            self._value = _to_array(data, dtype, place)
        self.stop_gradient = stop_gradient
        self.grad = None
        self._grad_node = None
        self._out_index = 0
        _tensor_counter[0] += 1
        self.name = f"generated_tensor_{_tensor_counter[0]}"
        self.persistable = False
        self._backward_hooks = None
        self.is_leaf_param = False
        ctx = _trace_state.ctx
        if ctx is not None:
            ctx.on_create(self)

    # ---- value access (trace-capture aware) ----
    def value(self):
        ctx = _trace_state.ctx
        if ctx is not None:
            from .lazy import LazyValue
            if isinstance(self._value, LazyValue):
                # a to_static trace must capture the concrete buffer,
                # not a half-built lazy segment
                self._value = self._value.force()
            return ctx.on_read(self)
        return self._value

    def _local_value_update(self, new_value):
        """Internal buffer swap that bypasses autograd (grad accumulation)."""
        self._value = new_value

    def _inplace_update(self, new_value, node=None, out_index=0):
        """In-place semantic update: swaps buffer and autograd metadata."""
        ctx = _trace_state.ctx
        if ctx is not None:
            ctx.on_write(self, self._value, self._grad_node)
        if _lazy._ENABLED:
            # optimizer param updates replace concrete buffers with
            # pending LazyValues: the old buffer is a donation candidate
            # for the flushed segment (params cost 1x HBM per step)
            _lazy.note_donation(self._value, new_value)
        self._value = new_value
        self._grad_node = node
        self._out_index = out_index

    def set_value(self, value):
        if isinstance(value, Tensor):
            v = value.value()
        else:
            v = _to_array(value, self.dtype, None)
        v = jnp.asarray(v, self._value.dtype)
        if tuple(v.shape) != tuple(self._value.shape):
            v = jnp.broadcast_to(v, self._value.shape)
        self._inplace_update(v)
        return self

    # ---- basic properties ----
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dim(self):
        return self._value.ndim

    @property
    def rank(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self) -> DType:
        return to_paddle_dtype(self._value.dtype)

    @property
    def place(self) -> Place:
        try:
            dev = list(self._value.devices())[0]
            if dev.platform == "cpu":
                return CPUPlace()
            return TPUPlace(dev.id)
        except Exception:
            return current_place()

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def T(self):
        from .. import ops
        return ops.linalg.t(self)

    @property
    def mT(self):
        from .. import ops
        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return ops.manipulation.transpose(self, perm)

    def numel(self):
        return to_tensor(self.size, dtype="int64")

    def element_size(self):
        return self.dtype.itemsize

    # ---- host interop ----
    def numpy(self):
        return np.asarray(self._value)

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    # ---- autograd ----
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], [grad_tensor], retain_graph=retain_graph)

    def clear_grad(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad._local_value_update(jnp.zeros_like(self.grad._value))
        else:
            self.grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self._value, _internal=True, stop_gradient=True)
        t.name = self.name + "@detach"
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def register_hook(self, hook):
        if self._backward_hooks is None:
            self._backward_hooks = []
        self._backward_hooks.append(hook)

        class _Removable:
            def __init__(self, hooks, h):
                self._hooks, self._h = hooks, h

            def remove(self):
                if self._h in self._hooks:
                    self._hooks.remove(self._h)

        return _Removable(self._backward_hooks, hook)

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # ---- conversion / device ----
    def astype(self, dtype):
        from .. import ops
        return ops.manipulation.cast(self, dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def cast_(self, dtype):
        self._inplace_update(
            jnp.asarray(self._value, to_jax_dtype(dtype)),
            self._grad_node, self._out_index)
        return self

    def cpu(self):
        cpu_dev = jax.devices("cpu")[0]
        return Tensor(jax.device_put(self._value, cpu_dev), _internal=True,
                      stop_gradient=self.stop_gradient)

    def cuda(self, device_id=None):
        return self.to_tpu(device_id)

    def tpu(self, device_id=None):
        return self.to_tpu(device_id)

    def to_tpu(self, device_id=None):
        devs = jax.devices()
        dev = devs[(device_id or 0) % len(devs)]
        return Tensor(jax.device_put(self._value, dev), _internal=True,
                      stop_gradient=self.stop_gradient)

    def pin_memory(self):
        return self

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str, Place)) and not isinstance(a, DType):
                if isinstance(a, str) and a in (
                        "float32", "float64", "float16", "bfloat16", "int32",
                        "int64", "int16", "int8", "uint8", "bool"):
                    t = t.astype(a)
                elif isinstance(a, Place):
                    t = t.cpu() if a.is_cpu_place() else t.to_tpu(a.device_id)
                else:
                    t = t.cpu() if a == "cpu" else t.to_tpu()
            elif isinstance(a, DType):
                t = t.astype(a)
        return t

    def clone(self):
        from .. import ops
        return ops.manipulation.clone(self)

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # ---- indexing ----
    def __getitem__(self, idx):
        from .. import ops
        return ops.manipulation.getitem(self, idx)

    def __setitem__(self, idx, value):
        from .. import ops
        ops.manipulation.setitem(self, idx, value)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        try:
            return bool(self.numpy())
        except (jax.errors.TracerBoolConversionError,
                jax.errors.TracerArrayConversionError) as e:
            # jax's traceback filtering re-raises from its own
            # sentinel, clobbering any __cause__ we chain — put the
            # advice in the message itself so it survives
            advice = (
                "python control flow on a traced Tensor (inside "
                "to_static / jit).  Use paddle.static.nn.cond / "
                "while_loop / switch_case, which lower to XLA control "
                "flow and stay traceable.")
            e.args = ((f"{e.args[0]}\n{advice}",) + e.args[1:]
                      if e.args else (advice,))
            raise

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __hash__(self):
        return id(self)

    def __format__(self, spec):
        if self.size == 1:
            return format(self.item(), spec)
        return str(self)

    def __repr__(self):
        try:
            vals = np.asarray(self._value)
            body = np.array2string(vals, precision=8, separator=", ")
        except Exception:
            body = "<uninitialized>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"place={self.place}, stop_gradient={self.stop_gradient},\n"
            f"       {body})"
        )

    # Arithmetic dunders and ~200 methods (add, sum, reshape, ...) are
    # attached by paddle_tpu.ops at import time — see ops/__init__.py.


def _to_array(data, dtype=None, place=None):
    if isinstance(data, Tensor):
        arr = data._value
        if dtype is not None:
            arr = jnp.asarray(arr, to_jax_dtype(dtype))
        return arr
    if isinstance(data, jax.Array):
        if dtype is not None:
            return jnp.asarray(data, to_jax_dtype(dtype))
        return data
    if isinstance(data, np.ndarray):
        from .dtypes import _X32_MAP, _X32_MODE
        jd = to_jax_dtype(dtype) if dtype is not None else data.dtype
        if dtype is None and data.dtype == np.float64 and not _X32_MODE:
            jd = np.float64  # paddle keeps float64 numpy arrays as float64
        if _X32_MODE:
            # canonicalize 64-bit inputs here so jnp neither warns nor
            # truncates per call under PADDLE_TPU_X32
            jd = _X32_MAP.get(np.dtype(jd), jd)
        return jnp.asarray(data, jd)
    # python scalars / nested lists
    if dtype is not None:
        return jnp.asarray(np.asarray(data), to_jax_dtype(dtype))
    arr = np.asarray(data)
    if arr.dtype == np.float64:
        # python floats default to the framework default dtype (float32)
        arr = arr.astype(to_jax_dtype(default_dtype()))
    return jnp.asarray(arr)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor."""
    arr = _to_array(data, dtype, place)
    if place is not None:
        if isinstance(place, str):
            from .place import set_device  # parse without mutating global
            p = Place("cpu", 0) if place == "cpu" else Place("tpu", 0)
        else:
            p = place
        arr = jax.device_put(arr, p.jax_device())
    return Tensor(arr, _internal=True, stop_gradient=stop_gradient)
