"""Device places, TPU-native.

Reference parity: phi::Place / GPUPlace / CPUPlace (`paddle/phi/common/place.h`
[UNVERIFIED]).  Here a Place names a JAX device.  ``TPUPlace`` is the
first-class accelerator place; ``CUDAPlace`` is provided as a compatibility
alias so reference-era scripts run unchanged (it maps to the default
accelerator).
"""
from __future__ import annotations

import functools

import jax

__all__ = [
    "Place", "CPUPlace", "TPUPlace", "CUDAPlace", "XPUPlace", "CustomPlace",
    "CUDAPinnedPlace", "set_device", "get_device", "get_all_devices",
    "current_place", "is_compiled_with_cuda", "is_compiled_with_tpu",
    "device_count",
]


@functools.lru_cache(maxsize=None)
def _backend_devices(backend=None):
    try:
        return tuple(jax.devices(backend) if backend else jax.devices())
    except RuntimeError:
        return ()


def _accel_backend() -> str:
    """The default accelerator backend name ('tpu' here; 'cpu' in tests)."""
    return jax.default_backend()


class Place:
    """Base place: (device_type, device_id)."""

    __slots__ = ("device_type", "device_id")

    def __init__(self, device_type: str, device_id: int = 0):
        self.device_type = device_type
        self.device_id = int(device_id)

    # -- paddle API --
    def is_cpu_place(self):
        return self.device_type == "cpu"

    def is_gpu_place(self):
        # On this framework the accelerator is the TPU; scripts probing
        # for "gpu" get the accelerator answer.
        return self.device_type in ("tpu", "gpu")

    def is_tpu_place(self):
        return self.device_type == "tpu"

    def get_device_id(self):
        return self.device_id

    def jax_device(self):
        backend = "cpu" if self.device_type == "cpu" else None
        devs = _backend_devices(None)
        if self.device_type == "cpu" and jax.default_backend() != "cpu":
            devs = _backend_devices("cpu")
        if not devs:
            raise RuntimeError(f"No devices for place {self}")
        return devs[self.device_id % len(devs)]

    def __repr__(self):
        return f"Place({self.device_type}:{self.device_id})"

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))


class CPUPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class TPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class XPUPlace(Place):
    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPlace(Place):
    """Compat alias: maps onto the accelerator (TPU)."""

    def __init__(self, device_id: int = 0):
        super().__init__("tpu", device_id)


class CUDAPinnedPlace(Place):
    def __init__(self):
        super().__init__("cpu", 0)


class CustomPlace(Place):
    def __init__(self, device_type: str = "tpu", device_id: int = 0):
        super().__init__(device_type, device_id)


_current_place: Place | None = None


def _default_place() -> Place:
    if jax.default_backend() == "cpu":
        return CPUPlace()
    return TPUPlace(0)


def current_place() -> Place:
    global _current_place
    if _current_place is None:
        _current_place = _default_place()
    return _current_place


def set_device(device) -> Place:
    """paddle.set_device('tpu') / 'tpu:1' / 'cpu' / 'gpu' (alias)."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    name = str(device)
    if ":" in name:
        kind, idx = name.split(":", 1)
        idx = int(idx)
    else:
        kind, idx = name, 0
    kind = kind.lower()
    if kind == "cpu":
        _current_place = CPUPlace()
    elif kind in ("tpu", "gpu", "cuda", "xpu", "npu"):
        _current_place = TPUPlace(idx)
    else:
        _current_place = CustomPlace(kind, idx)
    return _current_place


def get_device() -> str:
    p = current_place()
    if p.is_cpu_place():
        return "cpu"
    return f"{p.device_type}:{p.device_id}"


def get_all_devices():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def device_count() -> int:
    return len(jax.devices())


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False
