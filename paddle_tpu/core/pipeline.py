"""Async step pipeline primitives: lazy fetch handles + the bounded
in-flight window.

The dispatch stack (static ``Executor.run`` and ``jit.to_static``) used
to synchronize at every step boundary: feeds were converted on the
host, the executable dispatched, and every fetch pulled back to numpy
before the next step could start — h2d, compute, and d2h serialized.
On a remote/tunneled TPU that makes every step pay a full round trip
(ROUND5_NOTES measured dygraph configs at ~1 RTT/step).

This module is the synchronization policy for the async redesign:

  * ``FetchHandle`` — what ``Executor.run(..., return_numpy=False)``
    returns.  Holds the LIVE device array; the d2h transfer and
    ``block_until_ready`` happen on first read (``.numpy()``,
    ``float()``, ``np.asarray``), not inside ``run()``.  Reading is the
    sync point now.
  * ``InFlightWindow`` — a process-wide bound on un-synchronized
    dispatched steps (``PADDLE_TPU_PIPELINE_DEPTH``, default 2).  Every
    dispatch admits its outputs; when the window is full the OLDEST
    step is blocked on before the newest returns, so steps pipeline
    without unbounded HBM growth (the memory guard's pre-flight
    accounts for the extra in-flight buffers).  Depth 1 reproduces the
    fully synchronous semantics: each dispatch is blocked on before
    control returns to the caller.

Import discipline: this module may import only observability, jax, and
numpy — executor, jit, and io all import it and none of them may cycle.
"""
from __future__ import annotations

import os
import threading
from collections import deque

import numpy as np
import jax

from .. import observability as obs

__all__ = ["ENV_PIPELINE_DEPTH", "pipeline_depth", "FetchHandle",
           "InFlightWindow", "get_window", "drain"]

ENV_PIPELINE_DEPTH = "PADDLE_TPU_PIPELINE_DEPTH"
_DEFAULT_DEPTH = 2


def pipeline_depth():
    """Max dispatched-but-unsynchronized steps (>=1).  Read per call so
    tests (and the degradation ladder) can flip the env var live."""
    try:
        d = int(os.environ.get(ENV_PIPELINE_DEPTH, _DEFAULT_DEPTH))
    except ValueError:
        return _DEFAULT_DEPTH
    return max(1, d)


def _nbytes_of(values):
    n = 0
    for v in values:
        try:
            n += int(v.size) * v.dtype.itemsize
        except Exception:
            pass
    return n


class FetchHandle:
    """A fetch that has been dispatched but not synchronized.

    Wraps the live device array; the first host read (``numpy()``,
    ``__array__``, ``float()``, ``item()``) blocks until the step
    producing it completes and pays the d2h transfer, recorded as a
    ``d2h`` span.  ``shape``/``dtype`` never synchronize.
    """

    __slots__ = ("_value", "label", "step", "_host")

    def __init__(self, value, label=None, step=None):
        self._value = value
        self.label = label
        self.step = step
        self._host = None

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def value(self):
        """The live device array (no synchronization)."""
        return self._value

    def is_ready(self):
        try:
            return bool(self._value.is_ready())
        except Exception:
            return True

    def block_until_ready(self):
        jax.block_until_ready(self._value)
        return self

    def numpy(self):
        """The sync point: d2h + block_until_ready on first read."""
        if self._host is None:
            with obs.span("d2h:" + (self.label or "fetch"), cat="d2h",
                          step=self.step,
                          d2h_bytes=_nbytes_of((self._value,))):
                self._host = np.asarray(self._value)
        return self._host

    def tensor(self):
        """Wrap the device array as an eager Tensor (no host transfer)."""
        from .tensor import Tensor
        return Tensor(self._value, _internal=True, stop_gradient=True)

    def item(self):
        return self.numpy().item()

    def __array__(self, dtype=None):
        h = self.numpy()
        return h.astype(dtype) if dtype is not None else h

    def __float__(self):
        return float(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __bool__(self):
        return bool(self.numpy())

    def __len__(self):
        return int(self._value.shape[0])

    def __repr__(self):
        state = "ready" if self.is_ready() else "in-flight"
        return (f"FetchHandle({self.label or 'fetch'}, "
                f"shape={self.shape}, dtype={self.dtype}, {state})")


class InFlightWindow:
    """Bounded window of dispatched-but-unsynchronized steps.

    ``admit(values)`` registers one dispatch's output arrays; while
    more than ``depth - 1`` older dispatches remain unsynchronized the
    oldest is blocked on (recorded as a ``pipeline.wait`` span).  With
    depth 1 the admitted dispatch itself is blocked before ``admit``
    returns — bit-for-bit synchronous semantics.
    """

    def __init__(self, depth=None):
        self._depth = depth  # None → read the env per admit
        self._lock = threading.Lock()
        self._tickets = deque()

    def _resolve_depth(self):
        return self._depth if self._depth is not None else pipeline_depth()

    def __len__(self):
        with self._lock:
            return len(self._tickets)

    def admit(self, values, label=None, step=None):
        """Register one dispatch; blocks oldest steps past the bound."""
        depth = self._resolve_depth()
        values = tuple(values)
        with self._lock:
            self._tickets.append((values, label, step))
            n = len(self._tickets)
        if obs.enabled():
            obs.get_registry().gauge("pipeline.in_flight").set(n)
        while True:
            with self._lock:
                if len(self._tickets) <= depth - 1:
                    break
                oldest, olabel, ostep = self._tickets.popleft()
            with obs.span("pipeline.wait:" + (olabel or "step"),
                          cat="pipeline", step=ostep,
                          depth=depth):
                try:
                    jax.block_until_ready(oldest)
                except Exception:
                    pass  # deleted/donated buffer: already consumed
        if obs.enabled():
            obs.get_registry().gauge("pipeline.in_flight").set(len(self))

    def drain(self):
        """Block every outstanding step (loop exit / shutdown)."""
        while True:
            with self._lock:
                if not self._tickets:
                    break
                values, label, step = self._tickets.popleft()
            with obs.span("pipeline.wait:" + (label or "step"),
                          cat="pipeline", step=step):
                try:
                    jax.block_until_ready(values)
                except Exception:
                    pass  # deleted/donated buffer: already consumed
        if obs.enabled():
            obs.get_registry().gauge("pipeline.in_flight").set(0)


_window = None
_window_lock = threading.Lock()


def get_window():
    """The process-wide in-flight window every dispatcher admits into."""
    global _window
    if _window is None:
        with _window_lock:
            if _window is None:
                _window = InFlightWindow()
    return _window


def drain():
    """Synchronize all in-flight steps (module-level convenience)."""
    if _window is not None:
        _window.drain()
