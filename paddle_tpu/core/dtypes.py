"""Paddle-compatible dtype objects backed by JAX/numpy dtypes.

Reference parity: upstream Paddle exposes ``paddle.float32`` etc. as
``paddle.dtype`` instances (phi::DataType in C++, `paddle/phi/common/data_type.h`
[UNVERIFIED — reference mount empty, see SURVEY.md]).  Here each dtype is a
small interned object wrapping a numpy dtype that JAX understands natively
(bfloat16 via ml_dtypes, which numpy/jax ship).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

__all__ = [
    "DType", "dtype", "convert_dtype", "to_jax_dtype", "to_paddle_dtype",
    "uint8", "int8", "int16", "int32", "int64",
    "float16", "bfloat16", "float32", "float64",
    "complex64", "complex128", "bool_",
    "get_default_dtype", "set_default_dtype", "is_floating_point_dtype",
]


class DType:
    """A paddle.dtype-like interned dtype object."""

    _registry: dict[str, "DType"] = {}

    __slots__ = ("name", "np_dtype", "itemsize")

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        self.itemsize = self.np_dtype.itemsize
        DType._registry[name] = self

    def __repr__(self):
        return f"paddle.{self.name}"

    def __str__(self):
        return f"paddle.{self.name}"

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        if isinstance(other, str):
            try:
                return self.name == convert_dtype(other).name
            except (TypeError, ValueError):
                return False
        try:
            return self.np_dtype == np.dtype(other)
        except TypeError:
            return NotImplemented

    def is_floating_point(self):
        return self.name in ("float16", "bfloat16", "float32", "float64")

    def is_integer(self):
        return self.name in ("uint8", "uint16", "uint32", "uint64",
                             "int8", "int16", "int32", "int64")

    def is_complex(self):
        return self.name in ("complex64", "complex128")


# dtype alias, paddle exposes the class as ``paddle.dtype``
dtype = DType

uint8 = DType("uint8", np.uint8)
# u16/u32/u64 are not public Paddle dtypes but must round-trip through
# static Program Variables: JAX PRNG keys are uint32, and rng ops are
# recorded ops since the Executor threads generator state (VarDesc's
# UINT16/32/64 play the same internal role upstream)
uint16 = DType("uint16", np.uint16)
uint32 = DType("uint32", np.uint32)
uint64 = DType("uint64", np.uint64)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", ml_dtypes.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)
bool_ = DType("bool", np.bool_)

_NP_TO_PADDLE = {
    np.dtype(np.uint8): uint8,
    np.dtype(np.uint16): uint16,
    np.dtype(np.uint32): uint32,
    np.dtype(np.uint64): uint64,
    np.dtype(np.int8): int8,
    np.dtype(np.int16): int16,
    np.dtype(np.int32): int32,
    np.dtype(np.int64): int64,
    np.dtype(np.float16): float16,
    np.dtype(ml_dtypes.bfloat16): bfloat16,
    np.dtype(np.float32): float32,
    np.dtype(np.float64): float64,
    np.dtype(np.complex64): complex64,
    np.dtype(np.complex128): complex128,
    np.dtype(np.bool_): bool_,
}

_default_dtype = float32


def get_default_dtype() -> str:
    return _default_dtype.name


def set_default_dtype(d):
    global _default_dtype
    _default_dtype = convert_dtype(d)


def default_dtype() -> DType:
    return _default_dtype


def convert_dtype(d) -> DType:
    """Normalize anything dtype-like to a paddle DType object."""
    if d is None:
        return _default_dtype
    if isinstance(d, DType):
        return d
    if isinstance(d, str):
        name = d.removeprefix("paddle.")  # repr form, e.g. jit.save meta
        if name == "bool":
            return bool_
        if name in DType._registry:
            return DType._registry[name]
        # numpy-style strings like "f4"
        return _NP_TO_PADDLE[np.dtype(name)]
    npd = np.dtype(d)
    if npd in _NP_TO_PADDLE:
        return _NP_TO_PADDLE[npd]
    raise TypeError(f"Unsupported dtype: {d!r}")


import os as _os

_X32_MODE = _os.environ.get("PADDLE_TPU_X32") == "1"
_X32_MAP = {
    np.dtype(np.int64): np.dtype(np.int32),
    np.dtype(np.uint64): np.dtype(np.uint32),
    np.dtype(np.float64): np.dtype(np.float32),
    np.dtype(np.complex128): np.dtype(np.complex64),
}


def to_jax_dtype(d):
    """Paddle/str/np dtype -> numpy dtype usable by jnp.

    Under PADDLE_TPU_X32=1 (jax_enable_x64 left off) 64-bit requests
    canonicalize to 32-bit here, so explicit dtype= arguments neither
    warn nor re-upcast what jnp would silently downcast anyway."""
    npd = convert_dtype(d).np_dtype
    if _X32_MODE:
        return _X32_MAP.get(np.dtype(npd), npd)
    return npd


def to_paddle_dtype(jax_dtype) -> DType:
    return _NP_TO_PADDLE[np.dtype(jax_dtype)]


def is_floating_point_dtype(d) -> bool:
    return convert_dtype(d).is_floating_point()


def finfo(d):
    return jnp.finfo(to_jax_dtype(d))


def iinfo(d):
    return jnp.iinfo(to_jax_dtype(d))
