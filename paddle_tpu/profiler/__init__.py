"""paddle.profiler over the observability core (+ jax.profiler).

Reference parity: `python/paddle/profiler/` (Profiler with CLOSED→WARMUP→
RECORD scheduler, RecordEvent spans, chrome-trace export;
`fluid/platform/profiler/` host+CUPTI tracers) [UNVERIFIED — empty
reference mount].

Rebuilt as a thin shim over ``paddle_tpu.observability`` (ISSUE 3):
``RecordEvent`` records spans into the shared bounded timeline (plus an
XLA TraceAnnotation so the name shows in the device trace),
``Profiler.step()`` drives timeline step attribution,
``export_chrome_tracing`` serializes a real Perfetto-loadable trace
through the shared exporter, and ``summary()`` renders the shared op
view.  ``jax.profiler.start_trace/stop_trace`` still captures the
XLA/TPU XPlane timeline alongside, per the RECORD schedule.

A Profiler session force-enables collection for its duration (and
restores the prior ``PADDLE_TPU_OBS`` state on stop), so profiling
works without the env var; the session's host events are cleared on
stop after the ``on_trace_ready`` handler has consumed them.
"""
from __future__ import annotations

import os
import time
from enum import Enum

import jax

from .. import observability as _obs

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget", "ProfilerState",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SortedKeys", "SummaryView"]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    GPUTotal = 3
    GPUAvg = 4


class SummaryView(Enum):
    OverView = 0
    OpView = 1
    KernelView = 2


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """CLOSED×closed → READY×ready → RECORD×record, cycling; after
    ``repeat`` full cycles (0 = forever) the schedule stays CLOSED."""
    total = closed + ready + record

    def scheduler(step):
        s = step - skip_first
        if s < 0 or total <= 0:
            return ProfilerState.CLOSED
        if repeat and s >= repeat * total:
            return ProfilerState.CLOSED
        pos = s % total
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready handler factory: serialize the session's timeline
    as chrome-trace JSON under ``dir_name`` (Perfetto-loadable, via the
    shared exporter).  The written path is kept on
    ``prof._last_trace_path``."""
    def handler(prof):
        prof._log_dir = dir_name
        name = worker_name or f"worker_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}.pt.trace.json")
        prof._last_trace_path = _obs.export_chrome_trace(path)
        return prof._last_trace_path

    return handler


class RecordEvent:
    """Host-side span (shared timeline) + XLA TraceAnnotation (shows in
    the device timeline).  Recording follows the observability gate —
    a Profiler session enables it; so does ``PADDLE_TPU_OBS``."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None
        self._span = None

    def begin(self):
        self._span = _obs.span(self.name, cat="host")
        self._span.begin()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            self._ann = None
        if self._span is not None:
            self._span.end()
            self._span = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, **kwargs):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0],
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else
            (lambda step: ProfilerState.RECORD))
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._active = False
        self._log_dir = os.environ.get("PADDLE_TPU_PROFILE_DIR",
                                       "/tmp/paddle_tpu_profile")
        self._last_trace_path = None
        self._timer_only = timer_only
        self._step_times = []
        self._last_step_t = None
        self._prev_obs = None

    def start(self):
        self._prev_obs = _obs.enable(True)
        _obs.set_step(self._step)
        self._last_step_t = time.perf_counter()
        self._maybe_toggle()

    def stop(self):
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
        if self._on_trace_ready:
            self._on_trace_ready(self)
        # the handler has consumed the session's events; release the
        # bounded buffer so back-to-back sessions never accumulate
        _obs.get_timeline().clear()
        if self._prev_obs is not None:
            _obs.enable(self._prev_obs)
            self._prev_obs = None

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1
        _obs.set_step(self._step)
        self._maybe_toggle()

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        arr = np.asarray(self._step_times[-10:])
        return (f"avg step time {arr.mean() * 1000:.2f} ms "
                f"(min {arr.min() * 1000:.2f}, max {arr.max() * 1000:.2f})")

    def _maybe_toggle(self):
        if self._timer_only:
            return
        state = self._scheduler(self._step)
        should_record = state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN)
        if should_record and not self._active:
            try:
                jax.profiler.start_trace(self._log_dir)
                self._active = True
            except Exception:
                pass
        elif not should_record and self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        view = "step" if views == SummaryView.OverView else "op"
        lines = [_obs.summary(view=view)]
        # device memory footprint (SURVEY.md:101 allocator stats)
        from ..device import memory_stats
        s = memory_stats()
        if s:
            gb = 2.0 ** 30
            lines.append(
                f"{'HBM in_use / peak (GiB)':<44}"
                f"{s.get('bytes_in_use', 0)/gb:<8.3f}"
                f"{s.get('peak_bytes_in_use', 0)/gb:<12.3f}")
        out = "\n".join(lines)
        print(out)
        return out

    def export(self, path=None, format="json"):
        """Serialize the current timeline (chrome-trace json or jsonl)."""
        if format == "jsonl":
            return _obs.export_jsonl(path, append=False)
        return _obs.export_chrome_trace(path)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def load_profiler_result(filename):
    """Load an exported trace back (chrome-trace json or jsonl)."""
    import json
    try:
        if str(filename).endswith(".jsonl"):
            return _obs.load_jsonl(filename)
        with open(filename) as f:
            return json.load(f)
    except Exception:
        return None
