"""paddle.profiler over jax.profiler.

Reference parity: `python/paddle/profiler/` (Profiler with CLOSED→WARMUP→
RECORD scheduler, RecordEvent spans, chrome-trace export;
`fluid/platform/profiler/` host+CUPTI tracers) [UNVERIFIED — empty
reference mount].  TPU-native: jax.profiler captures XLA/TPU timelines
(XPlane → TensorBoard/perfetto); RecordEvent maps to TraceAnnotation.
"""
from __future__ import annotations

import contextlib
import os
import time
from enum import Enum

import jax

__all__ = ["Profiler", "RecordEvent", "ProfilerTarget", "ProfilerState",
           "make_scheduler", "export_chrome_tracing", "load_profiler_result",
           "SortedKeys", "SummaryView"]


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    TPU = 2
    CUSTOM_DEVICE = 3


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    GPUTotal = 3
    GPUAvg = 4


class SummaryView(Enum):
    OverView = 0
    OpView = 1
    KernelView = 2


def make_scheduler(closed=0, ready=0, record=1, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        total = closed + ready + record
        pos = s % total if total else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == total - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._log_dir = dir_name

    return handler


class _HostEvent:
    __slots__ = ("name", "start", "end")

    def __init__(self, name, start, end):
        self.name, self.start, self.end = name, start, end


_host_events = []


class RecordEvent:
    """Host-side span + XLA TraceAnnotation (shows in the TPU timeline)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None
        self._t0 = None

    def begin(self):
        self._t0 = time.perf_counter()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
        if self._t0 is not None:
            _host_events.append(
                _HostEvent(self.name, self._t0, time.perf_counter()))

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, **kwargs):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0],
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else
            (lambda step: ProfilerState.RECORD))
        self._on_trace_ready = on_trace_ready
        self._step = 0
        self._active = False
        self._log_dir = os.environ.get("PADDLE_TPU_PROFILE_DIR",
                                       "/tmp/paddle_tpu_profile")
        self._timer_only = timer_only
        self._step_times = []
        self._last_step_t = None

    def start(self):
        self._last_step_t = time.perf_counter()
        self._maybe_toggle()

    def stop(self):
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
        if self._on_trace_ready:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1
        self._maybe_toggle()

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        arr = np.asarray(self._step_times[-10:])
        return (f"avg step time {arr.mean() * 1000:.2f} ms "
                f"(min {arr.min() * 1000:.2f}, max {arr.max() * 1000:.2f})")

    def _maybe_toggle(self):
        if self._timer_only:
            return
        state = self._scheduler(self._step)
        should_record = state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN)
        if should_record and not self._active:
            try:
                jax.profiler.start_trace(self._log_dir)
                self._active = True
            except Exception:
                pass
        elif not should_record and self._active:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        from collections import defaultdict
        agg = defaultdict(lambda: [0.0, 0])
        for e in _host_events:
            agg[e.name][0] += (e.end - e.start) * 1000
            agg[e.name][1] += 1
        lines = [f"{'Name':<40}{'Calls':<8}{'Total(ms)':<12}"]
        for name, (tot, n) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
            lines.append(f"{name:<40}{n:<8}{tot:<12.3f}")
        # device memory footprint (SURVEY.md:101 allocator stats)
        from ..device import memory_stats
        s = memory_stats()
        if s:
            gb = 2.0 ** 30
            lines.append(
                f"{'HBM in_use / peak (GiB)':<40}"
                f"{s.get('bytes_in_use', 0)/gb:<8.3f}"
                f"{s.get('peak_bytes_in_use', 0)/gb:<12.3f}")
        out = "\n".join(lines)
        print(out)
        return out

    def export(self, path=None, format="json"):
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def load_profiler_result(filename):
    return None
